"""The annotation/label vocabulary: the user-facing config surface.

Per-notebook annotations and labels are the reference's third config layer
(SURVEY.md §5 "Config/flag system"); the names below preserve the reference's
wire contract (dashboards and users already speak it) and add the TPU-native
extensions under ``notebooks.kubeflow.org/tpu-*``.

Reference anchors: stop annotation
components/notebook-controller/pkg/culler/culler.go:41; restart
components/notebook-controller/controllers/notebook_controller.go:259-294;
activity stamps culling_controller.go:142-154; webhook annotations
components/odh-notebook-controller/controllers/notebook_mutating_webhook.go.
"""

# -- lifecycle ---------------------------------------------------------------
STOP = "kubeflow-resource-stopped"  # present → slice scaled to 0
RECONCILIATION_LOCK_VALUE = "odh-notebook-controller-lock"
RESTART = "notebooks.opendatahub.io/notebook-restart"
UPDATE_PENDING = "notebooks.opendatahub.io/update-pending"

# -- culling -----------------------------------------------------------------
LAST_ACTIVITY = "notebooks.kubeflow.org/last-activity"
LAST_ACTIVITY_CHECK = "notebooks.kubeflow.org/last_activity_check_timestamp"

# -- auth / webhook ----------------------------------------------------------
INJECT_AUTH = "notebooks.opendatahub.io/inject-auth"
AUTH_SIDECAR_CPU_REQUEST = "notebooks.opendatahub.io/auth-sidecar-cpu-request"
AUTH_SIDECAR_CPU_LIMIT = "notebooks.opendatahub.io/auth-sidecar-cpu-limit"
AUTH_SIDECAR_MEMORY_REQUEST = "notebooks.opendatahub.io/auth-sidecar-memory-request"
AUTH_SIDECAR_MEMORY_LIMIT = "notebooks.opendatahub.io/auth-sidecar-memory-limit"
LAST_IMAGE_SELECTION = "notebooks.opendatahub.io/last-image-selection"
WORKBENCH_IMAGE_NAMESPACE = "notebooks.opendatahub.io/workbench-image-namespace"
INJECT_OAUTH_LEGACY = "notebooks.opendatahub.io/inject-oauth"

# -- integrations ------------------------------------------------------------
MLFLOW_INSTANCE = "opendatahub.io/mlflow-instance"
# Istio routing overrides (reference notebook_controller.go:51-52).
REWRITE_URI = "notebooks.kubeflow.org/http-rewrite-uri"
HEADERS_REQUEST_SET = "notebooks.kubeflow.org/http-headers-request-set"
FEAST_INTEGRATION_LABEL = "opendatahub.io/feast-integration"
# Runtime-image sync (reference notebook_runtime.go:43-152).
RUNTIME_IMAGE_LABEL = "opendatahub.io/runtime-image"
RUNTIME_IMAGE_NAME = "opendatahub.io/runtime-image-name"

# -- TPU-native extensions ---------------------------------------------------
# Set by the culler when a slice host is preempted/evicted; cleared on recovery.
TPU_SLICE_INTERRUPTED = "notebooks.kubeflow.org/tpu-slice-interrupted"
# Recovery escalation state machine (controller/preemption.py). All four are
# controller-owned lifecycle state: unix-seconds timestamps / counters, never
# copied to pod templates (they would roll the StatefulSet).
# When the current interruption was first observed.
TPU_RECOVERY_STARTED = "notebooks.kubeflow.org/tpu-recovery-started"
# How many escalations (warm-pool claim or STS recreate) this interruption
# has consumed; past RecoveryConfig.max_escalations the state goes terminal.
TPU_RECOVERY_ESCALATIONS = "notebooks.kubeflow.org/tpu-recovery-escalations"
# When the most recent escalation fired (re-arms the recovery deadline).
TPU_RECOVERY_LAST_ESCALATION = "notebooks.kubeflow.org/tpu-recovery-last-escalation"
# Stamped on SliceRecovered with the interruption's wall-clock length, so
# runtime/checkpoint.py restore hints can key off how stale in-notebook
# state is. Survives until the next interruption completes.
TPU_LAST_INTERRUPTION_DURATION = (
    "notebooks.kubeflow.org/tpu-last-interruption-duration"
)
# Operator-set migration trigger (runtime/migration.py): stamping any value
# asks the controller to run one proactive live migration (save → warm-claim
# → restore → flip) for this Notebook's slice. The controller clears the
# annotation when it picks the trigger up, so the observed value doubles as
# a "migration requested but not yet started" marker. Controller-owned once
# consumed; never copied to pod templates.
TPU_MIGRATE_NOW = "notebooks.kubeflow.org/tpu-migrate-now"
# Event re-emission cursor: resourceVersion of the newest namespace Event
# already surfaced onto this Notebook (one read per reconcile, zero writes
# to Event objects, restart-safe because it lives on the Notebook).
LAST_SEEN_EVENT_RV = "notebooks.kubeflow.org/last-seen-event-rv"
# Webhook records the resolved slice shape so updates can be diffed cheaply.
TPU_RESOLVED_TOPOLOGY = "notebooks.kubeflow.org/tpu-resolved-topology"
# Serving quantization runtime option: "int8" | "int4" | "fp8" | "bf16".
# The webhook projects it into the KUBEFLOW_TPU_QUANT env var consumed by
# models.quant.quant_bits_from_env inside the notebook; the validating
# webhook rejects unknown values at admission.
TPU_QUANTIZATION = "notebooks.kubeflow.org/tpu-quantization"
TPU_QUANTIZATION_VALUES = ("int8", "int4", "fp8", "bf16")
QUANT_ENV_NAME = "KUBEFLOW_TPU_QUANT"
# Profiling runtime option: a port number makes runtime.bootstrap start
# jax.profiler.start_server on it; the controller surfaces the worker-0
# address as status.tpu.profilingServer and the ctrl NetworkPolicy opens
# the port to the controller/gateway namespaces (xprof/TensorBoard connect
# through a port-forward or the gateway).
TPU_PROFILING_PORT = "notebooks.kubeflow.org/tpu-profiling-port"
PROFILING_ENV_NAME = "KUBEFLOW_TPU_PROFILING_PORT"
# In-notebook HTTP inference endpoint (models/server.py): the webhook
# projects the port into KUBEFLOW_TPU_SERVING_PORT (examples/serve_http
# binds it), the ctrl NetworkPolicy opens it, and the controller surfaces
# worker-0's address as status.tpu.servingEndpoint. Same port rules as
# profiling (range at parse, reserved-ports at admission), plus the two
# annotations may not claim the SAME port on one notebook.
TPU_SERVING_PORT = "notebooks.kubeflow.org/tpu-serving-port"
SERVING_ENV_NAME = "KUBEFLOW_TPU_SERVING_PORT"
# Checkpoint durability contract (runtime/checkpoint.py). The grace
# annotation is seconds of termination grace the notebook wants for an
# emergency checkpoint on SIGTERM: the webhook projects it into
# TPU_CHECKPOINT_GRACE_S (bootstrap.install_preemption_handler budgets the
# final save with it) AND sizes the pod template's
# terminationGracePeriodSeconds (deploy.manifests.termination_grace_seconds
# adds the kill-path margin) so the kubelet actually waits that long.
TPU_CHECKPOINT_GRACE = "notebooks.kubeflow.org/tpu-checkpoint-grace-seconds"
CHECKPOINT_GRACE_ENV_NAME = "TPU_CHECKPOINT_GRACE_S"
# Where the checkpoint PVC is mounted inside the workbench container; the
# webhook always projects it for TPU notebooks (annotation overrides the
# default) so runtime code never hardcodes a path.
TPU_CHECKPOINT_DIR = "notebooks.kubeflow.org/tpu-checkpoint-dir"
CHECKPOINT_DIR_ENV_NAME = "KUBEFLOW_TPU_CHECKPOINT_DIR"
DEFAULT_CHECKPOINT_DIR = "/mnt/checkpoints"


def _load_reserved_ports() -> dict:
    from kubeflow_tpu.api import names

    return {
        names.NOTEBOOK_PORT: "the notebook server",
        names.RBAC_PROXY_PORT: "the kube-rbac-proxy sidecar",
        names.JAX_COORDINATOR_PORT: "the JAX distributed coordinator",
        names.MEGASCALE_PORT: "the multislice (megascale) coordinator",
    }


# Ports already claimed inside a notebook pod: a profiling server on any
# of these would collide at bootstrap (jax.profiler.start_server fails
# AFTER admission passed — exactly the late failure admission exists to
# prevent).
RESERVED_POD_PORTS = _load_reserved_ports()


def profiling_port_error(value) -> "str | None":
    """Why ``value`` would be DENIED at admission, or None if acceptable:
    the range rule plus the reserved-port rule. Reserved-port rejection
    is an ADMISSION concern only — it gates what new annotations may say,
    while parse_profiling_port (below) keeps honoring annotations that
    were admitted under older rules.
    Layered on parse_profiling_port so the range rule stays single-homed:
    the denial message can never diverge from what the consumers parse."""
    port = parse_profiling_port(value)
    if port is None:
        return f"{value!r} is not a port in 1024..65535"
    if port in RESERVED_POD_PORTS:
        return f"port {port} is already used in-pod by {RESERVED_POD_PORTS[port]}"
    return None


def parse_profiling_port(value) -> "int | None":
    """THE one parser for the profiling port (webhooks, NetworkPolicy,
    status, bootstrap all share it): a port in 1024..65535, else None.

    Deliberately RANGE-ONLY: tightening this parser with the reserved-port
    rule would retroactively invalidate notebooks admitted under older
    webhooks (their NetworkPolicy/status/bootstrap would silently stop
    seeing the port instead of surfacing a migration error). New objects
    with reserved ports never get this far — profiling_port_error denies
    them at admission.
    int() rather than isdigit() — Unicode digits like '²' pass isdigit()
    but crash int(), and an admission path must deny cleanly, not 500."""
    try:
        port = int(str(value).strip())
    except (TypeError, ValueError):
        return None
    return port if 1024 <= port <= 65535 else None

def parse_checkpoint_grace(value) -> "int | None":
    """THE one parser for the checkpoint-grace annotation (webhook env
    projection, terminationGracePeriodSeconds sizing, escalation-ladder
    messaging, in-pod bootstrap all share it): whole seconds in 1..3600,
    else None. The ceiling keeps a typo'd value from pinning a slice's
    nodes for hours after a delete; int() not isdigit() for the same
    Unicode-digit reason as parse_profiling_port."""
    try:
        grace = int(str(value).strip())
    except (TypeError, ValueError):
        return None
    return grace if 1 <= grace <= 3600 else None


# -- controller-owned markers ------------------------------------------------
# Marks image pre-pull pods (controller/prepull.py) so the reconciler can
# list exactly its own pods and the ctrl NetworkPolicy can exempt them.
PREPULL_LABEL = "notebooks.kubeflow.org/prepull"
# Platform-notebook finalizer (controller/platform.py): blocks Notebook
# deletion until the platform teardown (OAuth client, routes) ran.
PLATFORM_CLEANUP_FINALIZER = "notebooks.kubeflow.org/platform-cleanup"

# -- labels ------------------------------------------------------------------
NOTEBOOK_NAME_LABEL = "notebook-name"
ODH_DASHBOARD_LABEL = "opendatahub.io/dashboard"
