"""Shared resource names and ports: the cross-component wire contract.

The controller-side sync (kubeflow_tpu.controller.integrations) writes
objects the webhook-side mounts (kubeflow_tpu.webhook.mounts) look up by
name, and the env the webhook injects must match the ports the Services and
runtime bootstrap use. Each name/port is defined exactly once, here.
"""

NOTEBOOK_PORT = 8888
RBAC_PROXY_PORT = 8443
JAX_COORDINATOR_PORT = 8476  # jax.distributed default coordinator port
MEGASCALE_PORT = 8081  # megascale (multislice DCN) coordinator port

CA_BUNDLE_CONFIGMAP = "workbench-trusted-ca-bundle"
RUNTIME_IMAGES_CONFIGMAP = "pipeline-runtime-images"
ELYRA_SECRET_NAME = "ds-pipeline-config"
MANAGED_BY_LABEL = "opendatahub.io/managed-by"
MANAGED_BY_VALUE = "workbenches"
