"""Shared resource names and ports: the cross-component wire contract.

The controller-side sync (kubeflow_tpu.controller.integrations) writes
objects the webhook-side mounts (kubeflow_tpu.webhook.mounts) look up by
name, and the env the webhook injects must match the ports the Services and
runtime bootstrap use. Each name/port is defined exactly once, here.
"""

import hashlib

NOTEBOOK_PORT = 8888
RBAC_PROXY_PORT = 8443
JAX_COORDINATOR_PORT = 8476  # jax.distributed default coordinator port
MEGASCALE_PORT = 8081  # megascale (multislice DCN) coordinator port

def derived_name(base: str, suffix: str = "", limit: int = 63) -> str:
    """``{base}{suffix}`` when it fits ``limit``, else a deterministic
    hashed fallback: truncated base + 8-hex sha1(base) + suffix.

    Every child-object name derived from a Notebook name goes through
    this, so a long Notebook name degrades consistently everywhere
    (StatefulSets at 52 chars, Services/DNS labels at 63) instead of
    being rejected by the apiserver on whichever object overflows first.
    The reference's answer is apiserver GenerateName + controller-ref
    lookup (reference notebook_controller.go:145-149,444-447); a content
    hash keeps long names working without giving up get-by-name, which
    slice DNS, the culler, and cross-component lookups rely on.
    """
    candidate = f"{base}{suffix}"
    if len(candidate) <= limit:
        return candidate
    digest = hashlib.sha1(base.encode()).hexdigest()[:8]
    keep = limit - len(suffix) - len(digest) - 1
    return f"{base[:keep]}-{digest}{suffix}"


def routing_service_name(notebook_name: str) -> str:
    """The per-notebook routing Service (reference generateService :525)."""
    return derived_name(notebook_name, "", 63)


def proxy_service_name(notebook_name: str) -> str:
    """kube-rbac-proxy Service (reference notebook_kube_rbac_auth.go:95)."""
    return derived_name(notebook_name, "-kube-rbac-proxy", 63)


CA_BUNDLE_CONFIGMAP = "workbench-trusted-ca-bundle"
RUNTIME_IMAGES_CONFIGMAP = "pipeline-runtime-images"
ELYRA_SECRET_NAME = "ds-pipeline-config"
MANAGED_BY_LABEL = "opendatahub.io/managed-by"
MANAGED_BY_VALUE = "workbenches"
