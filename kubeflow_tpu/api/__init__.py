from kubeflow_tpu.api.notebook import (  # noqa: F401
    GROUP,
    KIND,
    HUB_VERSION,
    VERSIONS,
    Notebook,
    TPUSpec,
    new_notebook,
    convert,
)
from kubeflow_tpu.api import annotations  # noqa: F401
