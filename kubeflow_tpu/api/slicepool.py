"""SlicePool API: warm TPU slice capacity checked out at notebook spawn.

No reference counterpart — the reference treats pod spawn latency as the
cluster's problem (its only budget artifacts are CI timeouts; SURVEY.md §6).
On TPU the dominant spawn costs are node-pool provisioning and workbench
image pulls, both O(minutes) — far outside the <90 s p50 north star
(BASELINE.json) for a cold slice. A SlicePool holds ``warmReplicas``
pre-provisioned placeholder slices: each is a real indexed StatefulSet with
the same ``google.com/tpu`` resources and topology nodeSelectors a Notebook
slice would use (so GKE keeps nodes provisioned) running the workbench
image with an idle command (so kubelets keep the image pulled). When a
Notebook with a matching topology is created, the controller *claims* a
warm slice — deletes the placeholder, freeing its chips on already-warm
nodes for the notebook's pods to bind immediately — and the pool refills in
the background (level-triggered reconcile).

Pools are namespaced; a pool serves Notebooks in its own namespace (TPU
quota and RBAC are namespace-scoped in the multi-tenant layout).
"""

from __future__ import annotations

from typing import Any, Optional

from kubeflow_tpu.api.notebook import GROUP, TPUSpec
from kubeflow_tpu.k8s import objects as obj_util

KIND = "SlicePool"
VERSION = "v1"

# Labels stamped on placeholder StatefulSets; the claim path selects on them.
POOL_LABEL = "slicepools.kubeflow.org/pool"
STATE_LABEL = "slicepools.kubeflow.org/state"
ACCELERATOR_LABEL = "slicepools.kubeflow.org/accelerator"
TOPOLOGY_LABEL = "slicepools.kubeflow.org/topology"
STATE_WARM = "warm"

# Annotation recorded on the Notebook when its slice came from a pool.
CLAIMED_FROM = "notebooks.kubeflow.org/claimed-from-pool"

# Claim fence stamped ON THE PLACEHOLDER StatefulSet by the claim path,
# immediately before the delete, via an optimistic-concurrency update: the
# listed resourceVersion rides the write, so of two claimants racing one
# placeholder exactly one fence lands — the loser gets a Conflict and moves
# to the next candidate (controller.slicepool.ClaimLost). Without it the
# delete itself is check-then-act and both racers can believe they claimed
# the same slice.
CLAIMED_BY = "slicepools.kubeflow.org/claimed-by"

# Demand signals stamped ON THE POOL by the notebook reconciler's claim
# path (autoscaled pools only); the autoscaler keys off them. LAST_* are
# unix seconds (idle detection); MISS_COUNT is a monotonic counter so N
# concurrent misses scale the target by N, not by 1 (a timestamp alone
# collapses simultaneous demand).
LAST_CLAIM = "slicepools.kubeflow.org/last-claim"
LAST_MISS = "slicepools.kubeflow.org/last-miss"
MISS_COUNT = "slicepools.kubeflow.org/miss-count"


class SlicePool:
    """Typed view over a dict-shaped SlicePool object."""

    def __init__(self, obj: dict):
        self.obj = obj

    @property
    def name(self) -> str:
        return obj_util.name_of(self.obj)

    @property
    def namespace(self) -> str:
        return obj_util.namespace_of(self.obj)

    @property
    def tpu(self) -> TPUSpec:
        return TPUSpec.from_dict(self.obj.get("spec", {}).get("tpu", {}))

    @property
    def warm_replicas(self) -> int:
        return int(self.obj.get("spec", {}).get("warmReplicas", 1))

    @property
    def autoscale(self) -> Optional[dict]:
        """{"min", "max", "scaleDownAfterSeconds"} or None (fixed-size
        pool). When set, it REPLACES warmReplicas: the warm target starts
        at min, grows by one per claim-miss (up to max), and decays by one
        per idle scaleDownAfterSeconds (down to min). min=0 makes the pool
        purely demand-driven."""
        spec = self.obj.get("spec", {}).get("autoscale")
        if not spec:
            return None
        lo = int(spec.get("min", 0))
        # min > max is normalized to max = min (a CRD schema cannot express
        # the cross-field constraint; pinning the target above max forever
        # would be worse than honoring the larger bound).
        return {
            "min": lo,
            "max": max(lo, int(spec.get("max", 1))),
            "scaleDownAfterSeconds": int(spec.get("scaleDownAfterSeconds", 600)),
        }

    @property
    def image(self) -> str:
        """Image the placeholders run (and therefore keep pulled on the
        slice nodes). Default to the standard workbench image."""
        return self.obj.get("spec", {}).get("image", "jax-notebook:latest")

    @property
    def status(self) -> dict:
        return self.obj.setdefault("status", {})


def new_slicepool(
    name: str,
    namespace: str,
    tpu: TPUSpec,
    warm_replicas: int = 1,
    image: Optional[str] = None,
) -> dict:
    obj = obj_util.new_object(f"{GROUP}/{VERSION}", KIND, name, namespace)
    spec: dict[str, Any] = {
        "tpu": tpu.to_dict(),
        "warmReplicas": warm_replicas,
    }
    if image:
        spec["image"] = image
    obj["spec"] = spec
    return obj
