from kubeflow_tpu.tpu.topology import (  # noqa: F401
    Accelerator,
    SliceTopology,
    ACCELERATORS,
    parse_topology,
    slice_from_spec,
)
