"""TPU accelerator catalog and slice-topology math.

The reference control plane (opendatahub-io/kubeflow) treats accelerators as
an opaque PodSpec passthrough — there is no accelerator model anywhere in it
(reference: SURVEY.md, components/notebook-controller/controllers/
notebook_controller.go:433-523 simply copies the user PodSpec). This module is
the TPU-native replacement for that gap: it is the single source of truth that
turns a user-facing ``spec.tpu: {accelerator, topology}`` into

- chip / host counts (how many indexed-StatefulSet replicas a slice needs),
- GKE scheduling metadata (``cloud.google.com/gke-tpu-accelerator`` and
  ``cloud.google.com/gke-tpu-topology`` nodeSelectors, ``google.com/tpu``
  resource quantities),
- libtpu / JAX runtime environment (``TPU_WORKER_HOSTNAMES`` ordering,
  host/chip bounds).

Everything downstream (reconciler, webhook, culler, runtime bootstrap) calls
into this module rather than re-deriving topology facts.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional


class InvalidTopologyError(ValueError):
    """Raised when an accelerator/topology combination is not schedulable."""


@dataclasses.dataclass(frozen=True)
class Accelerator:
    """One TPU generation as exposed by GKE node pools.

    ``cores_per_chip`` exists because Google's accelerator-type naming is
    inconsistent across generations: v4/v5p type names count TensorCores
    (``v4-8`` is 4 chips) while v5e/v6e count chips (``v5litepod-4`` is
    4 chips).
    """

    name: str  # canonical short name: v4, v5e, v5p, v6e
    gke_label: str  # value of cloud.google.com/gke-tpu-accelerator
    dims: int  # topology dimensionality: 2 (v5e/v6e) or 3 (v4/v5p)
    chips_per_host: int  # chips on one host of a multi-host slice
    max_single_host_chips: int  # largest slice that fits on one host
    cores_per_chip: int  # for accelerator-type naming (see docstring)
    type_prefix: str  # accelerator-type string prefix, e.g. "v5litepod"
    hbm_gib_per_chip: int  # per-chip HBM, used for model-fit planning

    def type_name(self, chips: int) -> str:
        """Cloud accelerator-type string, e.g. ``v5litepod-16`` / ``v4-32``."""
        return f"{self.type_prefix}-{chips * self.cores_per_chip}"


ACCELERATORS: dict[str, Accelerator] = {
    "v4": Accelerator("v4", "tpu-v4-podslice", 3, 4, 4, 2, "v4", 32),
    "v5e": Accelerator("v5e", "tpu-v5-lite-podslice", 2, 4, 8, 1, "v5litepod", 16),
    "v5p": Accelerator("v5p", "tpu-v5p-slice", 3, 4, 4, 2, "v5p", 95),
    "v6e": Accelerator("v6e", "tpu-v6e-slice", 2, 4, 8, 1, "v6e", 32),
}

# User-facing aliases accepted in spec.tpu.accelerator.
_ALIASES = {
    "v5litepod": "v5e",
    "v5lite": "v5e",
    "tpu-v5-lite-podslice": "v5e",
    "tpu-v5-lite-device": "v5e",
    "tpu-v5p-slice": "v5p",
    "tpu-v4-podslice": "v4",
    "trillium": "v6e",
    "tpu-v6e-slice": "v6e",
    "tpu-v6e-device": "v6e",
}


def resolve_accelerator(name: str) -> Accelerator:
    key = name.strip().lower()
    key = _ALIASES.get(key, key)
    try:
        return ACCELERATORS[key]
    except KeyError:
        raise InvalidTopologyError(
            f"unknown TPU accelerator {name!r}; known: "
            f"{sorted(ACCELERATORS)} (aliases: {sorted(_ALIASES)})"
        ) from None


def parse_topology(topology: str) -> tuple[int, ...]:
    """Parse ``"4x4"`` / ``"2x2x2"`` into an int tuple."""
    parts = topology.strip().lower().split("x")
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError:
        raise InvalidTopologyError(f"malformed topology string {topology!r}") from None
    if not dims or any(d < 1 for d in dims):
        raise InvalidTopologyError(f"malformed topology string {topology!r}")
    return dims


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """A fully-resolved TPU slice: accelerator generation + physical topology.

    This is what the reconciler and webhook consume. ``hosts`` is the number
    of pods in the indexed StatefulSet; ``chips_per_host`` is the
    ``google.com/tpu`` resource request on each pod.
    """

    accelerator: Accelerator
    dims: tuple[int, ...]

    # -- basic counts ------------------------------------------------------
    @property
    def chips(self) -> int:
        return math.prod(self.dims)

    @property
    def single_host(self) -> bool:
        return self.chips <= self.accelerator.max_single_host_chips

    @property
    def chips_per_host(self) -> int:
        return self.chips if self.single_host else self.accelerator.chips_per_host

    @property
    def hosts(self) -> int:
        return 1 if self.single_host else self.chips // self.accelerator.chips_per_host

    # -- naming / scheduling metadata -------------------------------------
    @property
    def topology_str(self) -> str:
        return "x".join(str(d) for d in self.dims)

    @property
    def accelerator_type(self) -> str:
        return self.accelerator.type_name(self.chips)

    @property
    def gke_accelerator_label(self) -> str:
        return self.accelerator.gke_label

    def node_selector(self) -> dict[str, str]:
        return {
            "cloud.google.com/gke-tpu-accelerator": self.gke_accelerator_label,
            "cloud.google.com/gke-tpu-topology": self.topology_str,
        }

    # -- libtpu bounds -----------------------------------------------------
    def host_shape(self) -> tuple[int, ...]:
        """Chip grid owned by one host, e.g. (2, 2) on multi-host v5e."""
        if self.single_host:
            return self.dims
        if self.accelerator.dims == 2:
            return (2, 2)
        return (2, 2, 1)

    def host_bounds(self) -> tuple[int, ...]:
        """Host grid of the slice (dims / host_shape)."""
        shape = self.host_shape()
        return tuple(d // s for d, s in zip(self.dims, shape))

    def chip_bounds_str(self) -> str:
        """``TPU_CHIPS_PER_HOST_BOUNDS``-style string, always 3-D."""
        shape = self.host_shape() + (1,) * (3 - len(self.dims))
        return ",".join(str(s) for s in shape)

    def host_bounds_str(self) -> str:
        """``TPU_HOST_BOUNDS``-style string, always 3-D."""
        bounds = self.host_bounds() + (1,) * (3 - len(self.dims))
        return ",".join(str(b) for b in bounds)

    # -- slice DNS ---------------------------------------------------------
    def worker_hostnames(
        self, name: str, headless_service: str, namespace: str,
        cluster_domain: str = "cluster.local",
    ) -> list[str]:
        """Stable per-host DNS names in TPU_WORKER_ID order.

        Pod ``{name}-{i}`` of the indexed StatefulSet is TPU worker ``i``;
        the headless Service gives each a stable FQDN.
        """
        return [
            f"{name}-{i}.{headless_service}.{namespace}.svc.{cluster_domain}"
            for i in range(self.hosts)
        ]


def slice_from_spec(accelerator: str, topology: str) -> SliceTopology:
    """Validate and resolve a user-provided accelerator/topology pair."""
    acc = resolve_accelerator(accelerator)
    dims = parse_topology(topology)
    if len(dims) != acc.dims:
        raise InvalidTopologyError(
            f"{acc.name} topologies are {acc.dims}-D, got {topology!r}"
        )
    st = SliceTopology(acc, dims)
    if not st.single_host:
        shape = st.host_shape()
        for d, s in zip(dims, shape):
            if d % s != 0:
                raise InvalidTopologyError(
                    f"topology {topology!r} does not tile into {acc.name} hosts "
                    f"(host shape {'x'.join(map(str, shape))})"
                )
    return st
