"""Zigzag ring attention: load-balanced CAUSAL sequence parallelism.

The contiguous ring (parallel/ring_attention.py) computes every
(q-shard × k-chunk) score block and masks the causally-invisible half —
under a causal mask roughly HALF its FLOPs are thrown away, at every
sequence length. The fix from the context-parallelism literature
("zigzag"/"striped" scheduling): give each device a PAIRED shard — one
chunk from the sequence's front half and its mirror from the back half —
so every ring step carries exactly the same, fully-visible amount of
work on every device:

- the global sequence splits into ``2n`` chunks of C rows; device ``r``
  owns chunks ``(r, 2n-1-r)`` ("early", "late");
- at ring step ``s`` the received K/V pair originated on device
  ``c = (r - s) mod n``. For ``c < r`` BOTH of this device's q chunks see
  the received EARLY chunk and neither sees the late one; for ``c > r``
  only q_late sees anything — but it sees BOTH received chunks. Either
  way: exactly two C×C score products, all rows fully visible, no
  masking, no waste. Only step 0 (the local diagonal) computes three
  triangular/full products;
- partial softmaxes merge with the same lse recursion as the contiguous
  ring; K/V pairs rotate with ``ppermute`` exactly as before.

Total per device: ``2(n-1) + 3`` C×C products vs the contiguous ring's
``4n`` — the causal waste is gone (≈2× attention speedup at long S).

Layout contract: callers keep the NATURAL contiguous layout. The zigzag
redistribution happens INSIDE the shard_map body — two ``ppermute``s in
per tensor, two out. The owner maps are static permutations, and every
slot-selection table collapses to device-index PARITY (global chunk
``j`` sits in its zigzag owner's EARLY slot iff ``j < n``, and the
chunks routed through each ppermute alternate front/back half by the
sender's parity), so redistribution is cheap data movement with no
gather tables. Model code, rope positions, loss layout: all untouched —
``make_sharded_zigzag_attention`` is a drop-in ``sp_impl`` for
make_train_step.

Scope: causal, q_offset=0, no sliding window, no kv_mask (the balanced
schedule derives from pure causality; a windowed/masked variant would
re-introduce per-step imbalance). The ring/Ulysses impls keep full mask
parity; zigzag is the throughput path for plain causal training.

No reference counterpart (reference is a k8s controller); technique per
the public context-parallelism literature (PAPERS.md).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.parallel.ring_attention import (
    NEG_INF,
    cached_sharded,
    lse_merge,
    pick_kblock,
    safe_finish,
)


def _owner(j: int, n: int) -> int:
    """Zigzag owner of global chunk j (0..2n-1): front-half chunks go to
    their own index, back-half chunks mirror onto the same devices."""
    return j if j < n else 2 * n - 1 - j


def _flash_update(m, l, o, q, k, v, scale, tri=False):
    """lse-merge (m, l, o) with the scores q·kᵀ, processing k in
    sub-blocks of ≤ _RING_BLOCK (bounds the f32 score buffer — the same
    lever as ring_attention). ``tri`` applies the step-0 within-chunk
    causal triangle."""
    ck = k.shape[2]
    blk = pick_kblock(ck)

    def upd(carry, j):
        m, l, o = carry
        k_blk = jax.lax.dynamic_slice_in_dim(k, j * blk, blk, 2)
        v_blk = jax.lax.dynamic_slice_in_dim(v, j * blk, blk, 2)
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k_blk,
            preferred_element_type=jnp.float32,
        ) * scale
        if tri:
            q_pos = jnp.arange(q.shape[2])[:, None]
            k_pos = j * blk + jnp.arange(blk)[None, :]
            s = jnp.where((k_pos <= q_pos)[None, None], s, NEG_INF)
        return lse_merge(m, l, o, s, v_blk), None

    if ck // blk == 1:
        return upd((m, l, o), 0)[0]
    (m, l, o), _ = jax.lax.scan(
        jax.checkpoint(upd), (m, l, o), jnp.arange(ck // blk)
    )
    return m, l, o


def _flash_update_either(acc1, acc2, route1, q, k, v, scale):
    """lse-merge ONE of two accumulators with the scores q·kᵀ, chosen by
    the traced bool ``route1``: SELECT the target accumulator, run the
    recursion once (one QK product, one AV product), and scatter the
    result back — the un-chosen accumulator passes through untouched.
    This is how the two zigzag cases (c<r / c>r) share one SPMD program
    without duplicating any matmul."""
    sel = jax.tree.map(lambda a, b: jnp.where(route1, a, b), acc1, acc2)
    merged = _flash_update(*sel, q, k, v, scale)
    new1 = jax.tree.map(lambda m, a: jnp.where(route1, m, a), merged, acc1)
    new2 = jax.tree.map(lambda m, a: jnp.where(route1, a, m), merged, acc2)
    return new1, new2


def zigzag_ring_attention(
    q: jax.Array,  # local contiguous (B, H, 2C, D)
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
) -> jax.Array:
    """Balanced causal ring attention. MUST run inside shard_map over
    ``axis_name``; local shards are the NATURAL contiguous rows
    ``[r·2C, (r+1)·2C)`` — zigzag redistribution is internal."""
    n = jax.lax.psum(1, axis_name)  # static under shard_map
    r = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    if s_local % 2:
        raise ValueError(f"local sequence length {s_local} must be even")
    c_len = s_local // 2
    scale = 1.0 / math.sqrt(d)

    is_even = (r % 2) == 0  # traced bool — THE slot-selection table

    # Contiguous device r holds global chunks (2r, 2r+1) as halves; the
    # zigzag owner maps are static permutations:
    permA = [(i, _owner(2 * i, n)) for i in range(n)]      # routes h0
    permB = [(i, _owner(2 * i + 1, n)) for i in range(n)]  # routes h1
    # Inverses (output path): contiguous r takes chunk 2r from A's
    # sender, chunk 2r+1 from B's.
    invA = [(dst, src) for src, dst in permA]
    invB = [(dst, src) for src, dst in permB]

    def halves(x):
        return x[..., :c_len, :], x[..., c_len:, :]

    def to_zigzag(x):
        """Contiguous (2C) → (early chunk r, late chunk 2n-1-r)."""
        h0, h1 = halves(x)
        recvA = jax.lax.ppermute(h0, axis_name, permA)
        recvB = jax.lax.ppermute(h1, axis_name, permB)
        # recvA carries chunk 2r' = d's early chunk iff d == 2r' (d
        # even); parity decides the slot, uniformly.
        early = jnp.where(is_even, recvA, recvB)
        late = jnp.where(is_even, recvB, recvA)
        return early, late

    qe, ql = to_zigzag(q)
    ke, kl = to_zigzag(k)
    ve, vl = to_zigzag(v)

    # Two half-accumulators (q_early rows, q_late rows).
    def acc():
        return (
            jnp.full((b, h, c_len), NEG_INF, jnp.float32),
            jnp.zeros((b, h, c_len), jnp.float32),
            jnp.zeros((b, h, c_len, d), jnp.float32),
        )

    me, le, oe = acc()
    ml, ll, ol = acc()

    # Step 0 — the local diagonal: q_early×k_early (triangle),
    # q_late×k_late (triangle), q_late×k_early (chunk r < chunk 2n-1-r:
    # fully visible).
    me, le, oe = _flash_update(me, le, oe, qe, ke, ve, scale, tri=True)
    ml, ll, ol = _flash_update(ml, ll, ol, ql, kl, vl, scale, tri=True)
    ml, ll, ol = _flash_update(ml, ll, ol, ql, ke, ve, scale)

    ring_perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s_idx):
        (me, le, oe, ml, ll, ol, ke_c, kl_c, ve_c, vl_c) = carry
        # Rotate FIRST: step 0 (the local pair) already ran outside the
        # scan, so the body at s_idx computes against the pair that
        # originated s_idx hops upstream.
        ke_c = jax.lax.ppermute(ke_c, axis_name, ring_perm)
        kl_c = jax.lax.ppermute(kl_c, axis_name, ring_perm)
        ve_c = jax.lax.ppermute(ve_c, axis_name, ring_perm)
        vl_c = jax.lax.ppermute(vl_c, axis_name, ring_perm)
        c = (r - s_idx) % n
        case_lt = c < r  # traced bool
        # Product A: (q_early if c<r else q_late) × received EARLY chunk,
        # routed to the matching accumulator; ONE einsum either way.
        q_sel = jnp.where(case_lt, qe, ql)
        (me, le, oe), (ml, ll, ol) = _flash_update_either(
            (me, le, oe), (ml, ll, ol), case_lt, q_sel, ke_c, ve_c, scale
        )
        # Product B: q_late × (received EARLY if c<r else received LATE).
        k_sel = jnp.where(case_lt, ke_c, kl_c)
        v_sel = jnp.where(case_lt, ve_c, vl_c)
        ml, ll, ol = _flash_update(ml, ll, ol, ql, k_sel, v_sel, scale)
        return (me, le, oe, ml, ll, ol, ke_c, kl_c, ve_c, vl_c), None

    if n > 1:
        (me, le, oe, ml, ll, ol, _, _, _, _), _ = jax.lax.scan(
            step, (me, le, oe, ml, ll, ol, ke, kl, ve, vl),
            jnp.arange(1, n),
        )

    out_e = safe_finish(me, le, oe).astype(q.dtype)
    out_l = safe_finish(ml, ll, ol).astype(q.dtype)

    # Back to the contiguous layout: device r re-collects chunks
    # (2r, 2r+1). Sender d = owner(2r') forwards chunk 2r', which sits in
    # its EARLY slot iff d == 2r' — parity again.
    send_A = jnp.where(is_even, out_e, out_l)
    send_B = jnp.where(is_even, out_l, out_e)
    h0 = jax.lax.ppermute(send_A, axis_name, invA)
    h1 = jax.lax.ppermute(send_B, axis_name, invB)
    return jnp.concatenate([h0, h1], axis=2)


def make_sharded_zigzag_attention(mesh: Mesh):
    """Drop-in ``sp_impl`` callable for make_train_step: batch=(dp,fsdp),
    heads=tp, sequence=sp — signature-compatible with
    ops.attention.flash_attention, rejecting the masking options the
    balanced schedule cannot honor."""
    spec = P(("dp", "fsdp"), "tp", "sp", None)

    def body(q, k, v, **static):
        return zigzag_ring_attention(q, k, v, axis_name="sp")

    get = cached_sharded(mesh, body, (spec, spec, spec), spec, ())

    def attention(q, k, v, causal=True, q_offset=0, window=0, kv_mask=None,
                  impl=None):
        if not causal or q_offset or window or kv_mask is not None:
            raise ValueError(
                "zigzag sp attention is causal-only (no q_offset/window/"
                "kv_mask): its balanced schedule derives from pure "
                "causality — use sp_impl='ring' for masked variants"
            )
        return get(())(q, k, v)

    return attention
