"""Ulysses sequence parallelism: all-to-all head↔sequence re-sharding.

The second long-context strategy (alongside ring attention in
kubeflow_tpu.parallel.ring_attention): instead of rotating K/V around a
ring, two ``all_to_all`` collectives swap the sharded axis. Inbound, each
device trades its sequence shard for a HEAD shard — it then holds the FULL
sequence for H/sp heads and runs ordinary (pallas/XLA flash) attention
locally; outbound, the output is traded back to sequence shards.

Trade-off vs ring (the reason both exist):
- Ulysses moves activations twice (2 all-to-alls of O(S·D·H/sp) per
  device) regardless of sequence length; ring moves K/V sp-1 times but
  overlaps the permutes with compute.
- Ulysses runs one dense local attention — the pallas flash kernel applies
  unchanged, and the causal mask needs no cross-device bookkeeping.
- Ulysses caps sp at the head count (sp must divide H); ring has no such
  limit. GQA: K/V heads are repeated up to H first when sp does not
  divide n_kv_heads — correctness over bandwidth; prefer sp ≤ n_kv_heads
  on GQA configs.

Composition mirrors ring attention: batch over (dp, fsdp), heads over tp,
sequence over sp, all inside one shard_map.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from kubeflow_tpu.parallel.compat import shard_map

from kubeflow_tpu.ops.attention import flash_attention


def ulysses_attention(
    q: jax.Array,  # local (B, H_local, S_local, D) — H_local is post-tp
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    local_impl: str = "auto",
    q_offset: int = 0,
    window: int = 0,
    kv_mask=None,  # local (B, Sk_local) valid-key marks, sp-sharded
) -> jax.Array:
    """All-to-all attention. MUST run inside shard_map over ``axis_name``.

    Requires H_local % sp == 0 (after any GQA repeat done by the caller).
    Masking: after the inbound all-to-all each device holds the FULL
    sequence for its head group, so ``q_offset``/``window`` pass straight
    through to the local flash kernel; ``kv_mask`` arrives sequence-sharded
    (it has no head axis to trade) and is all-gathered over sp instead.
    """
    sp = jax.lax.psum(1, axis_name)
    if sp == 1:
        return flash_attention(
            q, k, v, causal=causal, impl=local_impl, q_offset=q_offset,
            window=window, kv_mask=kv_mask,
        )
    h_local = q.shape[1]
    if h_local % sp != 0:
        raise ValueError(
            f"ulysses needs heads ({h_local}) divisible by sp ({sp}); "
            "repeat GQA K/V heads or lower sp"
        )
    if kv_mask is not None:
        # The mask has no head axis to trade; all-gather the full row
        # instead. The local flash kernel (pallas or XLA) applies it.
        kv_mask = jax.lax.all_gather(
            kv_mask, axis_name, axis=1, tiled=True
        )  # (B, Sk) full
    # Trade sequence shards for head shards: (B, H, S/sp, D) → (B, H/sp, S, D).
    gather = partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=1,
        concat_axis=2, tiled=True,
    )
    out = flash_attention(
        gather(q), gather(k), gather(v), causal=causal, impl=local_impl,
        q_offset=q_offset, window=window, kv_mask=kv_mask,
    )
    # Trade back: (B, H/sp, S, D) → (B, H, S/sp, D).
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def make_sharded_ulysses_attention(mesh: Mesh, local_impl: str = "auto"):
    """Return attention(q, k, v, causal, q_offset, window, kv_mask)
    jit-composable over the full mesh — drop-in for
    make_sharded_ring_attention (same specs: batch=(dp,fsdp), heads=tp,
    sequence=sp)."""
    from kubeflow_tpu.parallel.ring_attention import cached_sharded

    spec = P(("dp", "fsdp"), "tp", "sp", None)
    sp = mesh.shape.get("sp", 1)

    def body(q, k, v, kv_mask=None, **static):
        return ulysses_attention(
            q, k, v, axis_name="sp", local_impl=local_impl,
            kv_mask=kv_mask, **static,
        )

    get = cached_sharded(
        mesh, body, (spec, spec, spec), spec,
        (("kv_mask", (P(("dp", "fsdp"), "sp"),)),),
    )

    def attention(q, k, v, causal=True, q_offset=0, window=0, kv_mask=None,
                  impl=None):
        h = q.shape[1]
        tp = mesh.shape.get("tp", 1)
        if (h // tp) % sp != 0:
            raise ValueError(
                f"heads-per-tp-shard {h // tp} not divisible by sp={sp}; "
                "the model layer must repeat GQA K/V up to full heads "
                "before sequence-parallel attention"
            )
        static = dict(causal=causal, q_offset=q_offset, window=window)
        if kv_mask is not None:
            return get((True,), **static)(q, k, v, kv_mask)
        return get((False,), **static)(q, k, v)

    return attention
