"""Device mesh + sharding plan for in-notebook model work.

TPU-first design (the scaling-book recipe): pick a mesh, annotate shardings
with NamedSharding/PartitionSpec, let XLA insert the collectives, which ride
ICI inside a slice and DCN across slices. Axes:

- ``dp``  — data parallel (batch dim; gradients all-reduced over dp)
- ``fsdp``— fully-sharded data parallel (params/optimizer sharded over it,
            all-gathered for use; batch also sharded over it)
- ``ep``  — expert parallel (MoE expert dim; token routing all_to_alls)
- ``pp``  — pipeline parallel (layer stages; activations ppermute between)
- ``tp``  — tensor parallel (attention heads / MLP hidden)
- ``sp``  — sequence/context parallel (ring attention over long sequences)

The reference control plane has no counterpart (SURVEY.md §2.5: parallelism
is "absent in reference"); this module is the in-notebook half of the
framework's distributed story.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    dp: int = 1, fsdp: int = 1, tp: int = 1, sp: int = 1,
    pp: int = 1, ep: int = 1,
    devices: Optional[list] = None,
) -> Mesh:
    """Build a Mesh with the canonical axis order (dp, fsdp, ep, pp, sp, tp).

    tp is innermost so tensor-parallel collectives ride the fastest ICI
    hops; dp is outermost so gradient all-reduces cross the slow links
    least often; pp sits between — its ppermute traffic is one activation
    per microbatch boundary, far lighter than tp/sp collectives.
    """
    devices = devices if devices is not None else jax.devices()
    want = dp * fsdp * ep * pp * sp * tp
    if want != len(devices):
        raise ValueError(
            f"mesh dp={dp} fsdp={fsdp} ep={ep} pp={pp} sp={sp} tp={tp} "
            f"needs {want} devices, have {len(devices)}"
        )
    arr = np.array(devices).reshape(dp, fsdp, ep, pp, sp, tp)
    return Mesh(arr, axis_names=("dp", "fsdp", "ep", "pp", "sp", "tp"))


@dataclass
class MeshPlan:
    """A mesh plus the PartitionSpecs the model stack agrees on."""

    mesh: Mesh

    @property
    def axes(self) -> dict:
        """Non-trivial mesh axes, ``{name: size}`` — the shape stamp
        /stats and the bench/loadtest provenance records carry so
        multi-chip and single-chip numbers are never conflated. Falls
        back to ``{"tp": 1}`` for a degenerate all-ones mesh (a plan
        was requested, so the record must still say so)."""
        sizes = {
            name: int(size)
            for name, size in self.mesh.shape.items()
            if int(size) > 1
        }
        return sizes or {"tp": 1}

    # -- activations -------------------------------------------------------
    @property
    def batch_spec(self) -> P:
        """Activations: batch over (dp, fsdp), sequence over sp."""
        return P(("dp", "fsdp"), "sp", None)

    @property
    def logits_spec(self) -> P:
        return P(("dp", "fsdp"), "sp", "tp")

    # -- parameters --------------------------------------------------------
    def param_spec(self, path: tuple[str, ...], value_ndim: int) -> P:
        """Sharding rule for a llama-family parameter by its tree path.

        tp shards the head/hidden output dimension; fsdp shards the input
        dimension (FSDP-style weight sharding). Stacked layer params carry
        a leading (n_layers,) axis that stays unsharded (the scan axis).
        Note: tp must divide n_kv_heads for GQA configs (e.g. tp ≤ 8 on
        llama-2-70b) or the wk/wv shard would split a head.
        """
        name = "/".join(path)
        if "fp8" in path:
            # fp8 delayed-scaling amax histories (models/fp8.py): a few
            # floats per layer, replicated — the projection-name match
            # below must not see "wq" in "layers/wq/fp8/x_hist" and hand
            # a 3-axis weight spec to a (L, history) meta.
            return P()
        if "embed" in name or "lm_head" in name:
            # (vocab, dim): vocab over tp, dim over fsdp
            return P("tp", "fsdp")
        if any(k in name for k in ("wq", "wk", "wv", "w_gate", "w_up")):
            # (L, dim, out): shard out over tp, dim over fsdp
            return P(None, "fsdp", "tp")
        if any(k in name for k in ("wo", "w_down")):
            # (L, in, dim): in over tp, dim over fsdp
            return P(None, "tp", "fsdp")
        return P()  # norms/scalars replicated

    def shard_params(self, params):
        """Apply NamedShardings to a param tree (device_put)."""
        def place(path, value):
            spec = self.param_spec(tuple(str(p.key) for p in path), value.ndim)
            return jax.device_put(value, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map_with_path(place, params)

    def shard_kv_cache(self, cache, seq_over_sp: bool = False):
        """device_put a stacked KV cache or paged block pool: the kv-head
        axis (2) over tp, the sequence/offset axis (3) over sp when
        ``seq_over_sp`` (dense serving caches; block pools shard by block
        ownership, so their offset axis stays unsharded). int8 scale
        leaves — one rank lower, no trailing head dim (models.llama
        init_kv_cache kv_bits=8) — follow their values. ONE home for the
        rank-dispatch rule AND its tp-divisibility precondition, so the
        serving engines cannot diverge. Raises when tp would split a kv
        head (GQA: a finer-than-head split silently corrupts attention)."""
        tp = self.mesh.shape.get("tp", 1)
        hkv = jax.tree_util.tree_leaves(cache)[0].shape[2]
        if hkv % max(1, tp):
            raise ValueError(
                f"tp={tp} must divide n_kv_heads={hkv} for sharded serving"
            )
        seq = "sp" if seq_over_sp else None

        def place(leaf):
            spec = (
                P(None, None, "tp", seq, None) if leaf.ndim == 5
                else P(None, None, "tp", seq)
            )
            return jax.device_put(leaf, NamedSharding(self.mesh, spec))

        return jax.tree.map(place, cache)

    def param_shardings(self, params):
        """NamedSharding tree (for jit in/out shardings)."""
        def spec_of(path, value):
            return NamedSharding(
                self.mesh,
                self.param_spec(tuple(str(p.key) for p in path), value.ndim),
            )

        return jax.tree_util.tree_map_with_path(spec_of, params)

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec)
