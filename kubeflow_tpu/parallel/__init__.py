from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh  # noqa: F401
from kubeflow_tpu.parallel.ring_attention import (  # noqa: F401
    make_sharded_ring_attention,
    ring_attention,
)
from kubeflow_tpu.parallel.ulysses import (  # noqa: F401
    make_sharded_ulysses_attention,
    ulysses_attention,
)
