from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh  # noqa: F401
