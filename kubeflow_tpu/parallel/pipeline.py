"""Pipeline parallelism over the ``pp`` mesh axis.

TPU-first design: stage parameters are sharded over ``pp`` (leading stacked
axis), and a GPipe microbatch schedule runs inside ``shard_map`` — each step
every stage computes its layers on its current activation, then the
activation rotates one stage forward via ``lax.ppermute`` (a single
neighbor-hop that rides ICI). The whole schedule is one ``lax.scan``, so XLA
sees a static loop with no data-dependent control flow.

The reference control plane has no counterpart (SURVEY.md §2.5); this is
part of the framework's in-notebook distributed story alongside ring
attention (sp) and FSDP/TP.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from kubeflow_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.models.llama import (
    LlamaConfig,
    _embed,
    _layer_fwd,
    _lm_head_logits,
    _norm,
    rope_frequencies,
)


def split_layers_into_stages(layers: dict, pp: int) -> dict:
    """Reshape stacked layer params (L, ...) → (pp, L/pp, ...)."""

    def reshape(x):
        L = x.shape[0]
        if L % pp:
            raise ValueError(f"n_layers={L} not divisible by pp={pp}")
        return x.reshape(pp, L // pp, *x.shape[1:])

    return jax.tree.map(reshape, layers)


def merge_stages_into_layers(staged: dict) -> dict:
    """Inverse of split_layers_into_stages."""
    return jax.tree.map(lambda x: x.reshape(-1, *x.shape[2:]), staged)


def _pipeline_spec(mesh: Mesh):
    """shard_map specs: stage params over pp, activations replicated."""
    stage_spec = P("pp")
    repl = P()
    return stage_spec, repl


def make_pipelined_apply(cfg: LlamaConfig, mesh: Mesh, n_micro: int):
    """Returns apply(staged_layers, x, cos, sin) -> x, running the layer
    stack pipelined over pp with ``n_micro`` microbatches.

    x: (B, S, D) with B % n_micro == 0. Embedding / final norm / lm_head
    stay outside (replicated) — stage 0/-1 placement of those is a
    memory optimization, not a correctness one.
    """
    pp = mesh.shape["pp"]
    stage_spec, repl = _pipeline_spec(mesh)

    def stage_fn(local_layers, x, cos, sin):
        def body(x, layer):
            return _layer_fwd(layer, cfg, x, cos, sin, "auto"), None

        x, _ = jax.lax.scan(body, x, local_layers)
        return x

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(stage_spec, repl, repl, repl),
        out_specs=repl,
        check_vma=False,
    )
    def pipelined(staged_layers, inputs, cos, sin):
        # staged_layers arrive as the local (1, L/pp, ...) shard.
        local = jax.tree.map(lambda t: t[0], staged_layers)
        idx = jax.lax.axis_index("pp")
        n_steps = n_micro + pp - 1
        buf = jnp.zeros_like(inputs[0])
        collected = jnp.zeros_like(inputs)

        def step(carry, t):
            buf, collected = carry
            # Stage 0 ingests microbatch t (clamped feed is masked out at
            # collection time for t >= n_micro).
            feed = inputs[jnp.clip(t, 0, n_micro - 1)]
            buf = jnp.where(idx == 0, feed, buf)
            y = stage_fn(local, buf, cos, sin)
            # Last stage has microbatch t-(pp-1)'s final activation.
            out_t = t - (pp - 1)
            slot = jnp.clip(out_t, 0, n_micro - 1)
            valid = (out_t >= 0) & (idx == pp - 1)
            collected = collected.at[slot].set(
                jnp.where(valid, y, collected[slot])
            )
            # Rotate activations one stage forward (ICI neighbor hop).
            buf = jax.lax.ppermute(
                y, "pp", [(i, (i + 1) % pp) for i in range(pp)]
            )
            return (buf, collected), None

        (buf, collected), _ = jax.lax.scan(
            step, (buf, collected), jnp.arange(n_steps)
        )
        # Only the last stage holds real outputs; replicate via masked psum.
        return jax.lax.psum(
            jnp.where(idx == pp - 1, collected, jnp.zeros_like(collected)), "pp"
        )

    def apply(staged_layers, x, cos, sin):
        b, s, d = x.shape
        if b % n_micro:
            raise ValueError(f"batch {b} not divisible by n_micro={n_micro}")
        inputs = x.reshape(n_micro, b // n_micro, s, d)
        out = pipelined(staged_layers, inputs, cos, sin)
        return out.reshape(b, s, d)

    return apply


def pipeline_forward(
    params: dict, cfg: LlamaConfig, tokens: jax.Array, mesh: Mesh, n_micro: int
) -> jax.Array:
    """Full forward with the layer stack pipelined; params['layers'] must be
    stage-stacked (pp, L/pp, ...)."""
    apply = make_pipelined_apply(cfg, mesh, n_micro)
    x = _embed(params, cfg, tokens)
    positions = jnp.arange(tokens.shape[1])
    cos, sin = rope_frequencies(cfg, positions)
    x = apply(params["layers"], x, cos, sin)
    x = _norm(x, params["final_norm"], cfg)
    return _lm_head_logits(x, params)


def shard_pipeline_params(params: dict, mesh: Mesh) -> dict:
    """Place stage-stacked layers over pp; the rest replicated."""

    def place(path, value):
        keys = "/".join(str(getattr(p, "key", p)) for p in path)
        spec = P("pp") if keys.startswith("layers") else P()
        return jax.device_put(value, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def make_pipeline_train_step(
    cfg: LlamaConfig, mesh: Mesh, n_micro: int, optimizer=None
):
    """(init_state, step): causal-LM training with pp-pipelined layers."""
    optimizer = optimizer or optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)

    def loss_fn(params, tokens):
        logits = pipeline_forward(params, cfg, tokens, mesh, n_micro)
        targets = tokens[:, 1:]
        logprobs = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    def init_state(params):
        return {
            "params": params,
            "opt_state": optimizer.init(params),
            "step": jnp.zeros((), jnp.int32),
        }

    def train_step(state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], tokens)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }, loss

    return init_state, jax.jit(train_step, donate_argnums=(0,))
