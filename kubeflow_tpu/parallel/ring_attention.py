"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context strategy (first-class per the framework brief): the sequence is
sharded over ``sp``; each device holds a Q/K/V shard, computes blockwise
attention against the K/V chunk it currently holds, and rotates K/V around
the ring with ``ppermute`` — overlapping compute with ICI transfers and
merging partial softmaxes with the standard log-sum-exp (flash) recursion.
Memory per device stays O(S/sp · D) while attending over the full sequence.

This is the jnp/shard_map formulation (XLA schedules the collective-compute
overlap); a pallas RDMA variant (pallas_guide.md "Ring Collectives") can
slot in underneath without changing the call site.

Composition with the rest of the mesh: ``make_sharded_ring_attention``
wraps the ring body in shard_map with batch over (dp, fsdp), heads over tp,
sequence over sp — so dp/tp/sp all compose in one jitted step.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

NEG_INF = -1e30


def ring_attention(
    q: jax.Array,  # local (B, H, S_local, D)
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Blockwise ring attention. MUST run inside shard_map over axis_name."""
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32) * scale

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, step_idx):
        m, l, o, k_cur, v_cur = carry
        # The chunk we currently hold originated on device (my_idx - step).
        chunk_idx = (my_idx - step_idx) % n
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            q_pos = my_idx * s_local + jnp.arange(s_local)[:, None]
            k_pos = chunk_idx * s_local + jnp.arange(s_local)[None, :]
            s = jnp.where((k_pos <= q_pos)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        # Rotate K/V to the next device; XLA overlaps this with the next
        # step's einsums.
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, o_new, k_next, v_next), None

    m0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    o0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    (m, l, o, _, _), _ = jax.lax.scan(
        step, (m0, l0, o0, k, v), jnp.arange(n)
    )
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def make_sharded_ring_attention(mesh: Mesh):
    """Return attention(q, k, v, causal, q_offset) jit-composable over the
    full mesh: batch=(dp,fsdp), heads=tp, sequence=sp."""
    spec = P(("dp", "fsdp"), "tp", "sp", None)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    def _sharded(q, k, v):
        return ring_attention(q, k, v, axis_name="sp", causal=True)

    def attention(q, k, v, causal=True, q_offset=0, impl=None):
        if not causal:
            raise NotImplementedError("ring attention is causal-only here")
        if q_offset:
            raise NotImplementedError(
                "ring attention does not support q_offset (cached "
                "continuation); the mask is anchored at position 0"
            )
        return _sharded(q, k, v)

    return attention
