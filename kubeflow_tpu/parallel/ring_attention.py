"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context strategy (first-class per the framework brief): the sequence is
sharded over ``sp``; each device holds a Q/K/V shard, computes blockwise
attention against the K/V chunk it currently holds, and rotates K/V around
the ring with ``ppermute`` — overlapping compute with ICI transfers and
merging partial softmaxes with the standard log-sum-exp (flash) recursion.
Memory per device stays O(S/sp · D) while attending over the full sequence.

Supported masking (full parity with ops.attention.flash_attention):
- ``causal`` with ``q_offset`` — cached continuation: the q shard's global
  positions start at ``q_offset`` (chunked long-prompt prefill under SP),
- ``window`` — Mistral-style sliding window; ring steps whose chunk lies
  entirely outside every query's window contribute nothing (their partial
  update is masked to -inf and the lse merge ignores them),
- ``kv_mask`` — (B, S_local) valid-key marks; the mask chunk rotates around
  the ring WITH its K/V chunk.

Decode (q_len == 1 against an sp-sharded KV cache) does not rotate anything:
``sp_decode_attention`` computes one partial (m, l, o) per device against
its local cache shard and merges across ``sp`` with three collectives
(pmax + 2 psum) — the flash-decoding split-KV reduction, which is one
ICI round instead of sp-1 ring steps.

This is the jnp/shard_map formulation (XLA schedules the collective-compute
overlap); a pallas RDMA variant (pallas_guide.md "Ring Collectives") can
slot in underneath without changing the call site.

Composition with the rest of the mesh: ``make_sharded_ring_attention``
wraps the ring body in shard_map with batch over (dp, fsdp), heads over tp,
sequence over sp — so dp/tp/sp all compose in one jitted step.
"""

from __future__ import annotations

import math
from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from kubeflow_tpu.parallel.compat import shard_map

NEG_INF = -1e30
# Key-width of the inner flash-style sub-block (see ring_attention): caps
# the materialized score buffer at (B, H, SqL, _RING_BLOCK) f32.
_RING_BLOCK = 1024


def lse_merge(m, l, o, s, v_blk):
    """One online-softmax (lse) recursion step shared by every SP
    attention impl here (ring, zigzag): fold the already-masked score
    block ``s`` and its values into the running (m, l, o)."""
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def pick_kblock(ck: int) -> int:
    """Key-width sub-block for the flash-style inner scan: the largest
    aligned divisor of ``ck`` up to _RING_BLOCK (single block if none)."""
    blk = next((c for c in (_RING_BLOCK, 512, 256, 128) if ck % c == 0), ck)
    return min(blk, ck)


def safe_finish(m, l, o):
    """Normalize + safe-softmax: rows with no visible keys output zero
    instead of normalized garbage (shared convention with
    ops.attention)."""
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.where((m > NEG_INF * 0.5)[..., None], out, 0.0)


def ring_attention(
    q: jax.Array,  # local (B, H, Sq_local, D)
    k: jax.Array,  # local (B, H, Sk_local, D)
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    q_offset: int = 0,
    window: int = 0,
    kv_mask: Optional[jax.Array] = None,  # local (B, Sk_local) valid keys
) -> jax.Array:
    """Blockwise ring attention. MUST run inside shard_map over axis_name.

    Global positions: the q shard on ring index ``r`` covers
    ``q_offset + r*Sq_local .. q_offset + (r+1)*Sq_local - 1``; the K/V
    chunk that ORIGINATED on ring index ``c`` covers
    ``c*Sk_local .. (c+1)*Sk_local - 1`` (K/V always anchor at 0 — they
    are the full cached context; q may be a later chunk of it).
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, sq_local, d = q.shape
    sk_local = k.shape[2]
    scale = 1.0 / math.sqrt(d)

    perm = [(i, (i + 1) % n) for i in range(n)]
    q_pos = q_offset + my_idx * sq_local + jnp.arange(sq_local)[:, None]

    # Long-context memory lever: process each held chunk in sub-blocks of
    # at most _RING_BLOCK keys with the same online-softmax recursion, so
    # the materialized score buffer is (B, H, SqL, block), not
    # (B, H, SqL, SkL) — at 32k-context shards the full matrix is GBs. The
    # rematerialized sub-body keeps backward memory at O(block) too.
    blk = pick_kblock(sk_local)
    nblk = sk_local // blk

    def update(m, l, o, k_blk, v_blk, mask_blk, k_start):
        """One flash-style (m, l, o) update against a key sub-block.
        Native-dtype MXU operands (bf16 in training — f32 operands would
        quarter the matmul rate), f32 accumulation + scale."""
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k_blk,
            preferred_element_type=jnp.float32,
        ) * scale
        k_pos = k_start + jnp.arange(blk)[None, :]
        if causal or window:
            mask = (k_pos <= q_pos) if causal else jnp.ones_like(k_pos <= q_pos)
            if window:
                mask = mask & (k_pos > q_pos - window)
            s = jnp.where(mask[None, None], s, NEG_INF)
        if mask_blk is not None:
            s = jnp.where(mask_blk[:, None, None, :], s, NEG_INF)
        return lse_merge(m, l, o, s, v_blk)

    def step(carry, step_idx):
        m, l, o, k_cur, v_cur, mask_cur = carry
        # The chunk we currently hold originated on device (my_idx - step).
        chunk_idx = (my_idx - step_idx) % n
        k_start0 = chunk_idx * sk_local
        if nblk == 1:
            m, l, o = update(m, l, o, k_cur, v_cur, mask_cur, k_start0)
        else:
            @jax.checkpoint
            def sub(carry2, j):
                m, l, o = carry2
                k_blk = jax.lax.dynamic_slice_in_dim(k_cur, j * blk, blk, 2)
                v_blk = jax.lax.dynamic_slice_in_dim(v_cur, j * blk, blk, 2)
                mask_blk = (
                    None if mask_cur is None
                    else jax.lax.dynamic_slice_in_dim(mask_cur, j * blk, blk, 1)
                )
                return update(
                    m, l, o, k_blk, v_blk, mask_blk, k_start0 + j * blk
                ), None

            (m, l, o), _ = jax.lax.scan(sub, (m, l, o), jnp.arange(nblk))
        # Rotate K/V (and the key-validity mask with them) to the next
        # device; XLA overlaps this with the next step's einsums.
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        mask_next = (
            None if mask_cur is None
            else jax.lax.ppermute(mask_cur, axis_name, perm)
        )
        return (m, l, o, k_next, v_next, mask_next), None

    m0 = jnp.full((b, h, sq_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq_local), jnp.float32)
    o0 = jnp.zeros((b, h, sq_local, d), jnp.float32)
    (m, l, o, _, _, _), _ = jax.lax.scan(
        step, (m0, l0, o0, k, v, kv_mask), jnp.arange(n)
    )
    return safe_finish(m, l, o).astype(q.dtype)


def sp_decode_attention(
    q: jax.Array,  # REPLICATED over sp: (B, H, Sq, D) — Sq small (1..K)
    k: jax.Array,  # local cache shard (B, Hkv, Skl, D), UNREPEATED (GQA)
    v: jax.Array,
    position,  # scalar or (Sq,); (B,) with per_batch=True
    axis_name: str = "sp",
    window: int = 0,
    kv_mask: Optional[jax.Array] = None,  # local (B, Skl) valid cache slots
    per_batch: bool = False,
    k_scale: Optional[jax.Array] = None,  # local (B, Hkv, Skl): int8 cache
    v_scale: Optional[jax.Array] = None,  #   scales (models.llama kv_bits=8)
) -> jax.Array:
    """Split-KV decode: each device attends its local KV-cache shard, then
    the partial softmaxes merge across ``sp`` with pmax/psum (the
    flash-decoding reduction). MUST run inside shard_map over axis_name.

    GQA-native: k/v carry their REAL head count (H % Hkv == 0); q folds
    to (B, Hkv, rep, Sq, D) against the unrepeated shard, so decode —
    which is KV-bandwidth-bound — never reads a rep-times-repeated cache.

    Device r's cache shard covers absolute slots r*Skl .. (r+1)*Skl-1.
    Query i attends slots <= position[i] (and > position[i]-window when
    windowed). ``per_batch`` positions are (B,) — continuous-batching
    decode, where every slot sits at its own offset (Sq == 1). Returns
    the merged (B, H, Sq, D) on every device.
    """
    my_idx = jax.lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    skl = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    qg = q.reshape(b, hkv, h // hkv, sq, d)
    # Native-dtype MXU operands, f32 accumulation (see ring step). An int8
    # cache shard (k_scale given) upcasts the VALUES to q's dtype for the
    # dot and folds the per-(head, position) scale into the f32 score
    # epilogue — same discipline as _gqa_decode_attention: only int8 bytes
    # ever cross HBM.
    s = jnp.einsum(
        "bgrqd,bgkd->bgrqk", qg, k.astype(q.dtype) if k_scale is not None
        else k, preferred_element_type=jnp.float32,
    ) * scale  # (B, G, R, Sq, Skl)
    if k_scale is not None:
        s = s * k_scale.astype(jnp.float32)[:, :, None, None, :]
    pos = jnp.asarray(position)
    k_pos = my_idx * skl + jnp.arange(skl)[None, :]
    if per_batch:
        q_pos = pos[:, None]  # (B, 1)
        mask = k_pos <= q_pos  # (B, Skl)
        if window:
            mask = mask & (k_pos > q_pos - window)
        s = jnp.where(mask[:, None, None, None, :], s, NEG_INF)
    else:
        if pos.ndim == 0:
            pos = jnp.broadcast_to(pos, (sq,))
        q_pos = pos[:, None]  # (Sq, 1)
        mask = k_pos <= q_pos
        if window:
            mask = mask & (k_pos > q_pos - window)
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, None, :], s, NEG_INF)
    m_local = jnp.max(s, axis=-1)  # (B, G, R, Sq)
    # Shards whose every slot is masked contribute exp(-inf)=0 cleanly.
    m = jax.lax.pmax(m_local, axis_name)
    p = jnp.exp(s - m[..., None])
    l = jax.lax.psum(jnp.sum(p, axis=-1), axis_name)
    if v_scale is not None:
        # Fold the value scales into the probabilities (cheap: (…, Skl) vs
        # dequantizing the (…, Skl, D) values).
        p = p * v_scale.astype(jnp.float32)[:, :, None, None, :]
    o = jax.lax.psum(
        jnp.einsum(
            "bgrqk,bgkd->bgrqd",
            p.astype(q.dtype if v_scale is not None else v.dtype),
            v.astype(q.dtype) if v_scale is not None else v,
            preferred_element_type=jnp.float32,
        ),
        axis_name,
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.where((m > NEG_INF * 0.5)[..., None], out, 0.0)  # safe softmax
    return out.reshape(b, h, sq, d).astype(q.dtype)


def cached_sharded(mesh: Mesh, body, base_specs, out_spec, opt_groups):
    """shard_map-builder shared by the SP attention factories: builds (and
    caches by static config) one shard_map whose OPTIONAL trailing inputs
    are present only when the caller passes them — so e.g. None-mask
    callers pay no dummy-mask bandwidth and repeat calls reuse the same
    traced closure.

    ``opt_groups`` is an ordered tuple of ``(name, specs)`` optional
    operand groups appended after the base operands when present. The
    returned ``get(present, **static)`` (``present``: tuple of bools
    aligned with opt_groups) yields a shard_map callable taking the base
    args plus each present group's operands in declaration order; inside,
    ``body(*base_args, **static, <name>=operand(s) or None)`` — a group
    with one spec arrives as a bare operand, a multi-spec group as a
    tuple.
    """
    cache: dict = {}
    n_base = len(base_specs)

    def get(present, **static):
        present = tuple(present)
        key = (present, tuple(sorted(static.items())))
        if key not in cache:
            in_specs = tuple(base_specs)
            for (_, specs), here in zip(opt_groups, present):
                if here:
                    in_specs += tuple(specs)

            @partial(
                shard_map, mesh=mesh, in_specs=in_specs,
                out_specs=out_spec, check_vma=False,
            )
            def _sharded(*args):
                rest = args[n_base:]
                opts = {}
                for (name, specs), here in zip(opt_groups, present):
                    if not here:
                        opts[name] = None
                        continue
                    take, rest = rest[:len(specs)], rest[len(specs):]
                    opts[name] = take[0] if len(specs) == 1 else take
                return body(*args[:n_base], **opts, **static)

            cache[key] = _sharded
        return cache[key]

    return get


def make_sharded_ring_attention(mesh: Mesh):
    """Return attention(q, k, v, causal, q_offset, window, kv_mask)
    jit-composable over the full mesh: batch=(dp,fsdp), heads=tp,
    sequence=sp. Signature-compatible with ops.attention.flash_attention
    so it can be passed as ``impl``."""
    spec = P(("dp", "fsdp"), "tp", "sp", None)

    def body(q, k, v, kv_mask=None, **static):
        return ring_attention(q, k, v, axis_name="sp", kv_mask=kv_mask,
                              **static)

    get = cached_sharded(
        mesh, body, (spec, spec, spec), spec,
        (("kv_mask", (P(("dp", "fsdp"), "sp"),)),),
    )

    def attention(q, k, v, causal=True, q_offset=0, window=0, kv_mask=None,
                  impl=None):
        static = dict(causal=causal, q_offset=q_offset, window=window)
        if kv_mask is not None:
            return get((True,), **static)(q, k, v, kv_mask)
        return get((False,), **static)(q, k, v)

    return attention


@lru_cache(maxsize=None)
def make_sharded_sp_decode(mesh: Mesh):
    """Return decode(q, k_shard, v_shard, position, window, kv_mask) with
    q replicated over sp and the KV cache sequence-sharded over sp —
    the serving-side counterpart of make_sharded_ring_attention. K/V may
    be GQA-unrepeated (head axis Hkv; tp must divide it). Memoized per
    mesh: the closure is a jit STATIC arg downstream (_cb_step), so a
    fresh closure per caller would recompile the whole serving step."""
    q_spec = P(("dp", "fsdp"), "tp", None, None)  # q NOT sharded over sp
    kv_spec = P(("dp", "fsdp"), "tp", "sp", None)
    scale_spec = P(("dp", "fsdp"), "tp", "sp")  # int8-cache (B, Hkv, Skl)

    def body(q, k, v, position, scales=None, kv_mask=None, **static):
        ks, vs = scales if scales is not None else (None, None)
        return sp_decode_attention(
            q, k, v, position, axis_name="sp", kv_mask=kv_mask,
            k_scale=ks, v_scale=vs, **static,
        )

    get = cached_sharded(
        mesh, body, (q_spec, kv_spec, kv_spec, P()), q_spec,
        (
            ("scales", (scale_spec, scale_spec)),
            ("kv_mask", (P(("dp", "fsdp"), "sp"),)),
        ),
    )

    def decode(q, k, v, position, window=0, kv_mask=None, per_batch=False,
               k_scale=None, v_scale=None):
        if (k_scale is None) != (v_scale is None):
            raise ValueError(
                "k_scale and v_scale must be passed together (int8 cache "
                "shards carry both, models.llama init_kv_cache kv_bits=8)"
            )
        position = jnp.asarray(position)
        args = (q, k, v, position)
        if k_scale is not None:
            args += (k_scale, v_scale)
        if kv_mask is not None:
            args += (kv_mask,)
        return get((k_scale is not None, kv_mask is not None),
                   window=window, per_batch=per_batch)(*args)

    return decode
