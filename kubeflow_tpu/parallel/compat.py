"""JAX API compatibility for the parallel kernels.

The kernels target the current ``jax.shard_map`` API (``check_vma=``
replication checking). Older JAX (≤ 0.4.x, still common on TPU VM images)
only ships ``jax.experimental.shard_map.shard_map`` with the kwarg spelled
``check_rep=``. This module presents the NEW surface on both: import
``shard_map`` from here instead of ``jax`` and pass ``check_vma=``.
"""

from __future__ import annotations

import functools

try:  # JAX ≥ 0.6: public API, check_vma kwarg.
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # JAX 0.4.x: experimental home, check_rep kwarg.
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


@functools.wraps(_shard_map)
def shard_map(f, **kwargs):
    check = kwargs.pop("check_vma", kwargs.pop("check_rep", None))
    if check is not None:
        kwargs[_CHECK_KW] = check
    return _shard_map(f, **kwargs)
