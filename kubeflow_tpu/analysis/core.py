"""kftpu-lint core: source modules, suppressions, constant resolution.

Everything here is pure ``ast`` — the engine never imports the code it
analyzes, so a module with a heavyweight import graph (jax, the webhook
stack) costs the same to lint as a leaf utility, and a broken module
surfaces as a ``parse-error`` finding instead of an ImportError.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator, Optional

# `# kftpu-lint: disable=<rule>[,<rule>...] — justification`
# The separator before the justification may be an em dash, `--`, or `:`;
# the justification itself is MANDATORY (enforced by the suppression rule,
# which cannot itself be suppressed).
SUPPRESS_RE = re.compile(
    r"#\s*kftpu-lint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(?:\s*(?:—|--|:)\s*(.*?))?\s*$"
)


@dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    col: int
    message: str
    suppressed: bool = False
    justification: str = ""
    baselined: bool = False  # matched a checked-in baseline entry
    out_of_diff: bool = False  # outside the --diff range's changed lines

    def render(self) -> str:
        mark = " (suppressed)" if self.suppressed else (
            " (baselined)" if self.baselined else ""
        )
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{mark}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
            "justification": self.justification,
            "baselined": self.baselined,
            "out_of_diff": self.out_of_diff,
        }


@dataclass
class Suppression:
    line: int  # 1-based line the comment sits on
    rules: tuple
    justification: str
    own_line: bool  # a stand-alone comment also covers the next line

    def covers(self, rule: str, line: int) -> bool:
        if rule not in self.rules:
            return False
        return line == self.line or (self.own_line and line == self.line + 1)


@dataclass
class SourceModule:
    """A parsed module plus the lookup tables the rules need."""

    path: Path  # absolute
    rel: str  # repo-relative posix path (display + home matching)
    name: str  # dotted module name (kubeflow_tpu.webhook.tpu_env) or stem
    tree: Optional[ast.Module]
    lines: list = field(default_factory=list)
    suppressions: list = field(default_factory=list)
    # Module-level NAME = "literal" assignments (any scope's top level is
    # fine for contract constants; we record module body only to keep the
    # table honest about what other modules can import).
    constants: dict = field(default_factory=dict)
    # local binding -> dotted target. `import a.b.c` binds "a"->"a";
    # `import a.b as x` binds "x"->"a.b"; `from a.b import N as y` binds
    # "y"->"a.b.N". Function-local imports are included (lazy-import
    # idiom is pervasive in runtime code).
    imports: dict = field(default_factory=dict)
    parents: dict = field(default_factory=dict)  # ast node -> parent node
    parse_error: Optional[str] = None
    _nodes: Optional[tuple] = field(default=None, repr=False)  # walk() cache

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        for sup in self.suppressions:
            if sup.covers(rule, line):
                return sup
        return None

    def walk(self) -> Iterator[ast.AST]:
        # ~20 rules each walk every module; flatten once and hand out
        # iterators over the cached tuple (the tree is never mutated).
        if self.tree is None:
            return iter(())
        nodes = self._nodes
        if nodes is None:
            nodes = self._nodes = tuple(ast.walk(self.tree))
        return iter(nodes)

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None


def _parse_suppressions(lines: list) -> tuple:
    sups, malformed = [], []
    for i, raw in enumerate(lines, start=1):
        if "kftpu-lint" not in raw or "disable" not in raw:
            continue  # prose mention, not a suppression marker
        m = SUPPRESS_RE.search(raw)
        if not m:
            # A kftpu-lint marker that doesn't parse is itself worth a
            # finding — a typo'd suppression silently suppresses nothing.
            malformed.append(i)
            continue
        rules = tuple(r.strip() for r in m.group(1).split(","))
        justification = (m.group(2) or "").strip()
        own_line = raw.lstrip().startswith("#")
        sups.append(Suppression(i, rules, justification, own_line))
    return sups, malformed


def _collect_constants(tree: ast.Module) -> dict:
    out = {}
    for node in tree.body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if not (isinstance(value, ast.Constant) and isinstance(value.value, str)):
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out[t.id] = value.value
    return out


def _collect_imports(tree: ast.Module, package: str) -> dict:
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    out[alias.asname] = alias.name
                else:
                    # `import a.b.c` binds only "a"; attribute chains are
                    # resolved segment-by-segment in resolve_str.
                    out.setdefault(alias.name.split(".")[0], alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                # Relative import: anchor on the module's own package.
                parts = package.split(".") if package else []
                parts = parts[: len(parts) - (node.level - 1)] if parts else []
                base = ".".join(parts + ([node.module] if node.module else []))
            for alias in node.names:
                if alias.name == "*":
                    continue
                out[alias.asname or alias.name] = f"{base}.{alias.name}" if base else alias.name
    return out


def load_module(path: Path, rel: str, name: str) -> SourceModule:
    text = path.read_text(encoding="utf-8")
    lines = text.splitlines()
    try:
        tree = ast.parse(text)
    except SyntaxError as err:
        return SourceModule(
            path, rel, name, None, lines, [], {}, {}, {},
            parse_error=f"{err.msg} (line {err.lineno})",
        )
    sups, malformed = _parse_suppressions(lines)
    package = name.rsplit(".", 1)[0] if "." in name else ""
    mod = SourceModule(
        path,
        rel,
        name,
        tree,
        lines,
        sups,
        _collect_constants(tree),
        _collect_imports(tree, package),
        {},
    )
    mod.malformed_suppression_lines = malformed
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            mod.parents[child] = parent
    return mod


# -- expression helpers ------------------------------------------------------


def dotted_parts(node: ast.AST) -> Optional[list]:
    """Flatten a Name/Attribute chain to its segments, or None."""
    parts = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        parts.reverse()
        return parts
    return None


def resolved_callee(mod: SourceModule, call: ast.Call) -> Optional[str]:
    """Canonical dotted name of the call target, first segment resolved
    through the module's import table ('t.sleep' -> 'time.sleep',
    from-imported 'sleep' -> 'time.sleep')."""
    parts = dotted_parts(call.func)
    if parts is None:
        return None
    head = mod.imports.get(parts[0], parts[0])
    return ".".join([head] + parts[1:])


def resolve_str(mod: SourceModule, node: ast.AST, index) -> Optional[str]:
    """Resolve an expression to a compile-time string: a literal, a local
    constant, or a (possibly aliased) reference to a constant in an
    indexed module. None when not statically resolvable."""
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, str) else None
    if isinstance(node, ast.Name):
        if node.id in mod.constants:
            return mod.constants[node.id]
        target = mod.imports.get(node.id)
        if target and "." in target:
            owner, attr = target.rsplit(".", 1)
            return index.get_constant(owner, attr)
        return None
    if isinstance(node, ast.Attribute):
        parts = dotted_parts(node)
        if not parts or len(parts) < 2:
            return None
        attr = parts[-1]
        base = parts[:-1]
        head = mod.imports.get(base[0], base[0])
        owner = ".".join([head] + base[1:])
        return index.get_constant(owner, attr)
    return None
