"""kftpu-lint JAX rules: hidden device->host syncs and eager collectives
on the serving path.

The serving engines budget for exactly one device->host readback per
step (the sampled-token fetch), and mark it with the ``host_`` naming
convention (``host_next = np.asarray(nxt)``). Anything else that forces
a sync inside the engine-step hot set — ``.item()``, ``float()/int()``
on a device array, ``np.asarray`` on a device value, ``jax.device_get``,
or a per-token Python loop dispatching device ops — serializes the
dispatch pipeline the ragged fused path exists to keep full (Ragged
Paged Attention, PAPERS.md arxiv 2604.15464).

"Hot" = reachable within config.HOT_PATH_DEPTH call-graph hops from the
roots in config.HOT_PATH_ROOTS (drive_once / _step / _step_ragged / the
ragged dispatch wrapper). Host-vs-device classification is local and
deliberately conservative: a local is *device* when bound from a
``jnp.*``/``jax.*`` call or a step-callable (config.DEVICE_PRODUCER_RE),
*host* when bound from ``np.*``, literals, or a ``host_*`` name —
everything else (parameters, attributes) is ambiguous and never flagged.

The second rule (CollectiveOutsideJit) guards the tensor-parallel
serving story: ``jax.lax.psum``/``all_gather``-family collectives only
make sense under a trace — inside jit (GSPMD inserts and fuses them) or
shard_map (the axis name exists there). An eager collective on the hot
path either crashes (unbound axis name) or, worse, silently runs a
gathered un-sharded fallback per step. "Traced" is the call-graph
closure of every function that is jit/pmap/shard_map-wrapped — by
decorator or by being passed (possibly through functools.partial) into
a wrapper call — so helpers like the ring-attention bodies that only
ever run inside a shard_map are never flagged.
"""

from __future__ import annotations

import ast
from typing import Optional

from kubeflow_tpu.analysis import config
from kubeflow_tpu.analysis.callgraph import direct_nodes
from kubeflow_tpu.analysis.core import (
    Finding,
    SourceModule,
    dotted_parts,
    resolved_callee,
)

_NP_CONVERTERS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}
_HOST_CALL_HEADS = ("np.", "numpy.")
_DEVICE_CALL_HEADS = ("jnp.", "jax.", "jax.numpy.")


def _is_device_callee(callee: Optional[str]) -> bool:
    if not callee:
        return False
    if callee.startswith(_DEVICE_CALL_HEADS):
        return True
    leaf = callee.rsplit(".", 1)[-1]
    return bool(config.DEVICE_PRODUCER_RE.match(leaf))


def _is_host_callee(callee: Optional[str]) -> bool:
    if not callee:
        return False
    return callee.startswith(_HOST_CALL_HEADS) or callee in (
        "int", "float", "len", "list", "sorted", "tuple", "dict",
    )


class _Locals:
    """Host/device classification of a function's simple local bindings."""

    def __init__(self, mod: SourceModule, fn_node: ast.AST):
        self.device: set = set()
        self.host: set = set()
        for node in direct_nodes(fn_node.body):
            if not isinstance(node, ast.Assign):
                continue
            side = self._side_of(mod, node.value)
            if side is None:
                continue
            for target in node.targets:
                elts = target.elts if isinstance(target, ast.Tuple) else [target]
                for elt in elts:
                    if isinstance(elt, ast.Name):
                        getattr(self, side).add(elt.id)

    def _side_of(self, mod: SourceModule, value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            callee = resolved_callee(mod, value)
            if callee is None:
                parts = dotted_parts(value.func)
                callee = parts[-1] if parts else None
            if _is_device_callee(callee):
                return "device"
            if _is_host_callee(callee):
                return "host"
            return None
        if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.Constant)):
            return "host"
        if isinstance(value, ast.Name):
            if value.id in self.device:
                return "device"
            if value.id in self.host or value.id.startswith(
                config.HOST_READBACK_PREFIX
            ):
                return "host"
        return None

    def _base_name(self, expr: ast.AST) -> Optional[str]:
        cur = expr
        while isinstance(cur, (ast.Subscript, ast.Attribute)):
            cur = cur.value
        return cur.id if isinstance(cur, ast.Name) else None

    def is_device(self, expr: ast.AST) -> bool:
        name = self._base_name(expr)
        return name is not None and name in self.device and not isinstance(
            expr, ast.Attribute
        )

    def is_host(self, expr: ast.AST) -> bool:
        name = self._base_name(expr)
        if name is None:
            return False
        return name in self.host or name.startswith(config.HOST_READBACK_PREFIX)


class HostSyncInHotPath:
    id = "kftpu-host-sync-in-hot-path"
    description = (
        "A hidden device->host sync (.item(), float()/int() on a device "
        "array, np.asarray of a device value, jax.device_get, or a "
        "per-token Python loop dispatching jnp/jax ops) inside the "
        "engine-step hot set (drive_once/_step/_step_ragged/the ragged "
        "dispatch wrapper). Each sync stalls dispatch for a full "
        "device round trip per step; batch the readback and bind the "
        "one deliberate per-step sync to a host_-prefixed local."
    )
    incidents = (
        "Ragged fused dispatch (PAPERS.md arxiv 2604.15464) exists to "
        "keep the device pipeline full; one stray .item() in _step "
        "re-serializes it",
    )
    docs = "ARCHITECTURE.md#static-analysis — JAX hot-path rules"

    def check_module(self, mod: SourceModule, index) -> list:
        return []

    def check_repo(self, index, checked: dict) -> list:
        graph = index.callgraph()
        hot: dict = {}  # key -> FunctionNode
        for fn in graph.functions.values():
            if fn.name not in config.HOT_PATH_ROOTS:
                continue
            rel = fn.mod.rel
            in_package = rel.startswith("kubeflow_tpu/")
            if in_package and not rel.startswith(
                config.HOT_PATH_MODULE_PREFIXES
            ):
                continue
            for node, _depth, _path in graph.reachable(
                fn, max_depth=config.HOT_PATH_DEPTH
            ):
                hot.setdefault(node.key, node)
        findings = []
        for fn in hot.values():
            if fn.mod.rel in checked:
                findings.extend(self._check_function(fn))
        return findings

    def _finding(self, fn, node, message) -> Finding:
        return Finding(
            self.id, fn.mod.rel, node.lineno, node.col_offset,
            f"{message} in hot-path function {fn.qualname}; " +
            "each hidden sync stalls the dispatch pipeline for a device "
            "round trip per step",
        )

    def _assign_target_is_host(self, mod: SourceModule, call: ast.Call) -> bool:
        parent = mod.parents.get(call)
        if isinstance(parent, ast.Assign):
            for target in parent.targets:
                if isinstance(target, ast.Name) and target.id.startswith(
                    config.HOST_READBACK_PREFIX
                ):
                    return True
        return False

    def _check_function(self, fn) -> list:
        mod = fn.mod
        locals_ = _Locals(mod, fn.node)
        findings = []
        for node in direct_nodes(fn.node.body):
            if isinstance(node, ast.Call):
                callee = resolved_callee(mod, node) or ""
                if callee == "jax.device_get":
                    findings.append(
                        self._finding(fn, node, "jax.device_get() forces a "
                                      "device->host transfer")
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                    and not locals_.is_host(node.func.value)
                ):
                    findings.append(
                        self._finding(fn, node, ".item() is a blocking "
                                      "device->host sync")
                    )
                elif callee in _NP_CONVERTERS and node.args:
                    if locals_.is_device(node.args[0]) and \
                            not self._assign_target_is_host(mod, node):
                        findings.append(
                            self._finding(
                                fn, node,
                                f"{callee}() on a device value is a "
                                "blocking sync — if this is the one "
                                "deliberate per-step readback, bind it "
                                "to a host_-prefixed local",
                            )
                        )
                elif callee in ("float", "int") and node.args:
                    if locals_.is_device(node.args[0]):
                        findings.append(
                            self._finding(
                                fn, node,
                                f"{callee}() on a device array syncs; "
                                "read it back once via a host_ local "
                                "and index that",
                            )
                        )
            elif isinstance(node, ast.For):
                findings.extend(self._check_loop(fn, mod, node))
        return findings

    def _check_loop(self, fn, mod: SourceModule, loop: ast.For) -> list:
        if not (
            isinstance(loop.iter, ast.Call)
            and (resolved_callee(mod, loop.iter) or "") == "range"
        ):
            return []
        for node in direct_nodes(loop.body):
            if isinstance(node, ast.Call):
                callee = resolved_callee(mod, node) or ""
                if callee.startswith(_DEVICE_CALL_HEADS):
                    return [
                        self._finding(
                            fn, loop,
                            f"per-token Python loop dispatches {callee} "
                            "each iteration — fuse it into the batched "
                            "dispatch or jit the loop body",
                        )
                    ]
        return []


# Collectives only exist under a trace: psum/all_gather resolve their
# axis name against the enclosing jit's mesh or shard_map's axis binding.
_COLLECTIVE_LEAVES = {
    "psum", "pmean", "pmax", "pmin",
    "all_gather", "all_to_all", "ppermute", "psum_scatter",
}
_TRACE_WRAPPER_LEAVES = {"jit", "pmap", "shard_map"}


def _wrapper_leaf(callee: Optional[str]) -> Optional[str]:
    if not callee:
        return None
    leaf = callee.rsplit(".", 1)[-1]
    return leaf if leaf in _TRACE_WRAPPER_LEAVES else None


def _collective_callee(mod: SourceModule, call: ast.Call) -> Optional[str]:
    """'jax.lax.psum' when the call is a lax-family collective, else None."""
    callee = resolved_callee(mod, call)
    if callee is None:
        parts = dotted_parts(call.func)
        callee = ".".join(parts) if parts else None
    if not callee:
        return None
    parts = callee.split(".")
    if parts[-1] not in _COLLECTIVE_LEAVES:
        return None
    if "lax" in parts[:-1] or parts[0] == "jax":
        return callee
    return None


class CollectiveOutsideJit:
    id = "kftpu-collective-outside-jit"
    description = (
        "A jax.lax collective (psum/pmean/pmax/pmin/all_gather/all_to_all/"
        "ppermute/psum_scatter) called from the serving hot set outside any "
        "jitted or shard_map region. Collectives resolve their axis name "
        "against the enclosing trace; eagerly they raise an unbound-axis "
        "error at best and serialize a per-step gathered fallback at "
        "worst. Move the collective into the jitted step body, or wrap "
        "the caller in jax.jit/shard_map."
    )
    incidents = (
        "Tensor-parallel serving replicas (models/tp_serving.py) rely on "
        "every tp psum living inside the jitted fused step; one eager "
        "collective on the drive path breaks the mesh replica while the "
        "single-chip engine keeps passing",
    )
    docs = "ARCHITECTURE.md#static-analysis — JAX hot-path rules"

    def check_module(self, mod: SourceModule, index) -> list:
        return []

    def check_repo(self, index, checked: dict) -> list:
        graph = index.callgraph()
        traced = self._traced_closure(graph)
        hot: dict = {}
        for fn in graph.functions.values():
            if fn.name not in config.HOT_PATH_ROOTS:
                continue
            rel = fn.mod.rel
            in_package = rel.startswith("kubeflow_tpu/")
            if in_package and not rel.startswith(
                config.HOT_PATH_MODULE_PREFIXES
            ):
                continue
            for node, _depth, _path in graph.reachable(
                fn, max_depth=config.HOT_PATH_DEPTH
            ):
                hot.setdefault(node.key, node)
        findings = []
        for fn in hot.values():
            if fn.mod.rel not in checked or fn.key in traced:
                continue
            for node in direct_nodes(fn.node.body):
                if not isinstance(node, ast.Call):
                    continue
                callee = _collective_callee(fn.mod, node)
                if callee is None:
                    continue
                findings.append(Finding(
                    self.id, fn.mod.rel, node.lineno, node.col_offset,
                    f"{callee}() in hot-path function {fn.qualname} runs "
                    "outside any jit/shard_map region; the axis name is "
                    "unbound eagerly — move the collective into the "
                    "jitted step body or wrap the caller",
                ))
        return findings

    # -- traced-region closure ----------------------------------------------

    def _traced_closure(self, graph) -> set:
        """Keys of every function under a trace: jit/pmap/shard_map-wrapped
        (decorator, or passed — possibly via functools.partial — into a
        wrapper call anywhere in its module) plus everything call-graph
        reachable from one; a traced caller traces its callees."""
        entries = [
            fn for fn in graph.functions.values() if self._decorated(fn)
        ]
        for mod in graph.index.modules.values():
            if mod.tree is None:
                continue
            names = self._wrapped_names(mod)
            if not names:
                continue
            for fname, fns in graph.module_defs.get(mod.name, {}).items():
                if fname in names:
                    entries.extend(fns)
        traced: set = set()
        for entry in entries:
            if entry.key in traced:
                continue
            for node, _depth, _path in graph.reachable(entry, max_depth=None):
                traced.add(node.key)
        return traced

    def _decorated(self, fn) -> bool:
        for dec in fn.node.decorator_list:
            if isinstance(dec, ast.Call):
                callee = resolved_callee(fn.mod, dec) or ""
                if _wrapper_leaf(callee):
                    return True
                if callee.rsplit(".", 1)[-1] == "partial" and dec.args:
                    parts = dotted_parts(dec.args[0])
                    if parts and parts[-1] in _TRACE_WRAPPER_LEAVES:
                        return True
            else:
                parts = dotted_parts(dec)
                if parts and parts[-1] in _TRACE_WRAPPER_LEAVES:
                    return True
        return False

    def _wrapped_names(self, mod: SourceModule) -> set:
        """Function names passed into a jit/pmap/shard_map call in mod,
        directly or as the first argument of a functools.partial."""
        names: set = set()
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            callee = resolved_callee(mod, node)
            if callee is None:
                parts = dotted_parts(node.func)
                callee = ".".join(parts) if parts else None
            if not _wrapper_leaf(callee):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
                elif isinstance(arg, ast.Call):
                    inner = resolved_callee(mod, arg) or ""
                    if inner.rsplit(".", 1)[-1] == "partial" and arg.args \
                            and isinstance(arg.args[0], ast.Name):
                        names.add(arg.args[0].id)
        return names


JAX_RULES = [HostSyncInHotPath(), CollectiveOutsideJit()]
