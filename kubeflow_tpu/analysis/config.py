"""kftpu-lint configuration: contract homes and declared allowlists.

Every allowlist entry carries a mandatory reason string, mirroring the
inline-suppression rule: nothing gets exempted silently.
"""

from __future__ import annotations

import re

# -- contract homes (repo-relative posix paths / prefixes) -------------------

# THE spelling sites for platform env var names. A TPU_*/JAX_*/MEGASCALE_*/
# KUBEFLOW_TPU_* string literal anywhere else is a finding.
ENV_CONTRACT_MODULE = "kubeflow_tpu/webhook/tpu_env.py"
ENV_NAME_HOMES = (
    ENV_CONTRACT_MODULE,
    "kubeflow_tpu/api/annotations.py",
)

# THE spelling site for notebooks.kubeflow.org/* style annotation, label,
# and finalizer keys (plus the rest of the kubeflow_tpu/api constants).
ANNOTATION_HOME_PREFIX = "kubeflow_tpu/api/"

# Metric families register here and nowhere else.
METRICS_MODULE = "kubeflow_tpu/metrics/metrics.py"

# Chaos experiment handlers register here; chaos/experiments/*.yaml is the
# declarative side of the same catalog.
CHAOS_CATALOG_MODULE = "kubeflow_tpu/k8s/chaos_catalog.py"
CHAOS_EXPERIMENTS_DIR = "chaos/experiments"

# The linter does not lint its own rule tables (this package encodes the
# contract names it checks for — every one would be a self-finding).
SELF_PREFIX = "kubeflow_tpu/analysis/"

# -- patterns ----------------------------------------------------------------

ENV_NAME_RE = re.compile(r"^(TPU|JAX|MEGASCALE|KUBEFLOW_TPU)_[A-Z0-9_]+$")
METRIC_NAME_RE = re.compile(r"^(tpu_|notebook_|last_notebook_)[a-z0-9_]+$")
TPU_METRIC_RE = re.compile(r"^tpu_[a-z0-9_]+$")
ANNOTATION_RE = re.compile(
    r"^(notebooks\.(kubeflow|opendatahub)\.org|opendatahub\.io)/[A-Za-z0-9._/-]+$"
)
# Prometheus exposition suffixes a literal may legitimately carry on top
# of the registered family name (Histogram series, counter _created).
METRIC_SERIES_SUFFIXES = ("_count", "_sum", "_bucket", "_created")

# -- allowlists --------------------------------------------------------------

# Env vars that may be read without appearing in ENV_CONTRACT, and whose
# names may be spelled at their owning read site: name -> reason.
ENV_READ_ALLOWLIST = {
    "JAX_PLATFORMS": (
        "owned by the operator/test harness (backend selector); the "
        "platform honors it but never produces it"
    ),
    "KUBEFLOW_TPU_FORCE_XLA_ATTENTION": (
        "debug kill switch owned by ops/attention.py; deliberately not "
        "part of the webhook contract"
    ),
}

# The reference controller's metric set (notebook-controller
# pkg/metrics/metrics.go:22-60) predates the tpu_* scheme; dashboards
# already speak these names.
REFERENCE_METRIC_NAMES = {
    "notebook_create_total",
    "notebook_create_failed_total",
    "notebook_culling_total",
    "last_notebook_culling_timestamp_seconds",
    "notebook_running",
}

# Non-metric attributes and methods that legitimately hang off a Metrics
# object (rule metric-attr-unregistered).
METRICS_OBJECT_API = {
    "registry",
    "client",
    "collect_running",
    "expose",
}

# Prometheus metric constructor names (resolved through imports where
# possible; a bare Name falls back to this set).
PROM_CONSTRUCTORS = {"Counter", "Gauge", "Histogram", "Summary"}

# -- metric/stats parity (rule metric-stats-parity) --------------------------

# Serving, engine, gateway, autoscaler, and migration metric families
# must stay visible in the servers' JSON /stats payload; the STATS_PARITY
# table in metrics/metrics.py maps each family to the /stats key that
# surfaces it (gateway/autoscaler families surface under the gateway's
# own /stats; migration families under the orchestrator's stats block).
STATS_PARITY_FAMILY_RE = re.compile(
    r"^tpu_(serving|engine|gateway|autoscaler|migration)_[a-z0-9_]+$"
)

# Where /stats payloads are assembled: every STATS_PARITY value must
# appear as a string literal in one of these modules.
STATS_SURFACE_MODULES = (
    "kubeflow_tpu/models/server.py",
    "kubeflow_tpu/models/gateway.py",
    "kubeflow_tpu/models/autoscaler.py",
    "kubeflow_tpu/runtime/migration.py",
)
