"""kftpu-lint configuration: contract homes and declared allowlists.

Every allowlist entry carries a mandatory reason string, mirroring the
inline-suppression rule: nothing gets exempted silently.
"""

from __future__ import annotations

import re

# -- contract homes (repo-relative posix paths / prefixes) -------------------

# THE spelling sites for platform env var names. A TPU_*/JAX_*/MEGASCALE_*/
# KUBEFLOW_TPU_* string literal anywhere else is a finding.
ENV_CONTRACT_MODULE = "kubeflow_tpu/webhook/tpu_env.py"
ENV_NAME_HOMES = (
    ENV_CONTRACT_MODULE,
    "kubeflow_tpu/api/annotations.py",
)

# THE spelling site for notebooks.kubeflow.org/* style annotation, label,
# and finalizer keys (plus the rest of the kubeflow_tpu/api constants).
ANNOTATION_HOME_PREFIX = "kubeflow_tpu/api/"

# Metric families register here and nowhere else.
METRICS_MODULE = "kubeflow_tpu/metrics/metrics.py"

# Chaos experiment handlers register here; chaos/experiments/*.yaml is the
# declarative side of the same catalog.
CHAOS_CATALOG_MODULE = "kubeflow_tpu/k8s/chaos_catalog.py"
CHAOS_EXPERIMENTS_DIR = "chaos/experiments"

# The linter does not lint its own rule tables (this package encodes the
# contract names it checks for — every one would be a self-finding).
SELF_PREFIX = "kubeflow_tpu/analysis/"

# -- patterns ----------------------------------------------------------------

ENV_NAME_RE = re.compile(r"^(TPU|JAX|MEGASCALE|KUBEFLOW_TPU)_[A-Z0-9_]+$")
METRIC_NAME_RE = re.compile(r"^(tpu_|notebook_|last_notebook_)[a-z0-9_]+$")
TPU_METRIC_RE = re.compile(r"^tpu_[a-z0-9_]+$")
ANNOTATION_RE = re.compile(
    r"^(notebooks\.(kubeflow|opendatahub)\.org|opendatahub\.io)/[A-Za-z0-9._/-]+$"
)
# Prometheus exposition suffixes a literal may legitimately carry on top
# of the registered family name (Histogram series, counter _created).
METRIC_SERIES_SUFFIXES = ("_count", "_sum", "_bucket", "_created")

# -- allowlists --------------------------------------------------------------

# Env vars that may be read without appearing in ENV_CONTRACT, and whose
# names may be spelled at their owning read site: name -> reason.
ENV_READ_ALLOWLIST = {
    "JAX_PLATFORMS": (
        "owned by the operator/test harness (backend selector); the "
        "platform honors it but never produces it"
    ),
    "KUBEFLOW_TPU_FORCE_XLA_ATTENTION": (
        "debug kill switch owned by ops/attention.py; deliberately not "
        "part of the webhook contract"
    ),
}

# The reference controller's metric set (notebook-controller
# pkg/metrics/metrics.go:22-60) predates the tpu_* scheme; dashboards
# already speak these names.
REFERENCE_METRIC_NAMES = {
    "notebook_create_total",
    "notebook_create_failed_total",
    "notebook_culling_total",
    "last_notebook_culling_timestamp_seconds",
    "notebook_running",
}

# Non-metric attributes and methods that legitimately hang off a Metrics
# object (rule metric-attr-unregistered).
METRICS_OBJECT_API = {
    "registry",
    "client",
    "collect_running",
    "expose",
}

# Prometheus metric constructor names (resolved through imports where
# possible; a bare Name falls back to this set).
PROM_CONSTRUCTORS = {"Counter", "Gauge", "Histogram", "Summary"}

# -- interprocedural analysis (callgraph.py / concurrency.py / jaxrules.py) --

# Reachability bound for the shared call graph. Deep enough for every
# real chain in the repo (handler -> gateway -> replica source -> claim
# walk is 4 hops); bounded so a pathological cycle cannot explode a rule.
CALLGRAPH_MAX_DEPTH = 8

# Dynamic-dispatch fallback: an untyped `obj.m()` resolves only when at
# most this many repo classes define `m`. Above the cap the call
# contributes no edges — a wrong edge is worse than a missing one.
DISPATCH_CAP = 3

# Method names too ubiquitous to dispatch on receiver-blind: almost every
# container/IO/logging object has these, so a name match means nothing.
DISPATCH_SKIP_NAMES = {
    "get", "put", "items", "keys", "values", "append", "pop", "add",
    "close", "read", "write", "inc", "dec", "set", "observe", "labels",
    "info", "debug", "warning", "error", "exception", "join", "split",
    "update", "copy", "encode", "decode", "strip", "lower", "upper",
    "format", "start", "send", "recv", "flush", "clear", "discard",
    "remove", "extend", "insert", "count", "index", "setdefault",
}

# Lock-protocol method names never resolve through the dispatch fallback:
# `q.all_tasks_done.acquire()` must not grow an edge into some repo
# class's `acquire`. They still resolve through *typed* receivers
# (learned attr types or ATTR_TYPE_HINTS below).
LOCK_PROTOCOL_METHODS = {
    "acquire", "release", "wait", "wait_for", "notify", "notify_all",
    "locked",
}

# Attribute types the analyzer cannot learn from `self.x = Cls(...)`
# because the attribute is only ever assigned from a constructor
# parameter: (class, attr) -> (type, reason). Extend this table when you
# add a new injected collaborator whose methods matter to the
# concurrency rules (see CONTRIBUTING.md "Modeling locks and threads").
ATTR_TYPE_HINTS = {
    ("ServingGateway", "replica_source"): (
        "WarmSliceReplicaSource",
        "injected via __init__ param; acquire() walks the k8s claim "
        "deadline and must be visible to kftpu-lock-held-await",
    ),
    ("FleetAutoscaler", "gateway"): (
        "ServingGateway",
        "injected via __init__ param; tick() reads gateway.stats() and "
        "the lock-order rules must see the edge",
    ),
    ("WarmSliceProvisioner", "gateway"): (
        "ServingGateway",
        "injected via __init__ param; scale paths re-enter the gateway",
    ),
}

# Methods that are thread entry points by convention even without an
# explicit Thread(target=...) in scope: the repo's loop-method naming.
# Thread targets, signal registrations, and BaseHTTPRequestHandler do_*
# methods are discovered structurally; this set only adds the loops
# whose Thread(...) spawn site passes them by variable.
THREAD_ENTRY_METHODS = {
    "run", "tick", "_drive", "_drain", "_loop", "_probe_loop",
    "_health_loop",
}

# kftpu-lock-held-await follows calls this many hops past the with-block
# (depth >= 1 only — depth-0 blocking calls are lock-held-blocking-call's
# single-function territory).
LOCK_AWAIT_DEPTH = 4

# Call-graph depth for lock-set propagation in the shared-write and
# lock-order analyses.
LOCK_PROPAGATION_DEPTH = 6

# Dotted-callee suffixes that can block indefinitely when reached with a
# lock held (kftpu-lock-held-await), beyond the _blocking_reason set.
BLOCKING_AWAIT_CALLEES = {
    "http.client.HTTPConnection": "HTTP connection",
    "http.client.HTTPSConnection": "HTTPS connection",
    "urllib.request.urlopen": "network I/O (urlopen)",
    "subprocess.run": "subprocess",
    "subprocess.Popen": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "time.sleep": "time.sleep()",
}

# Functions whose body is a bounded-deadline remote walk: blocking by
# nature, so reaching one with a lock held is a finding by itself.
BLOCKING_AWAIT_FUNCTIONS = {
    "claim_warm_slice": "k8s warm-slice claim walk (bounded, but seconds)",
}

# -- kftpu-host-sync-in-hot-path ---------------------------------------------

# The engine-step hot set: serving-path functions where a hidden
# device->host sync serializes the data path. Reachability from these
# roots (bounded by HOT_PATH_DEPTH) defines "hot".
HOT_PATH_ROOTS = {"drive_once", "_step", "_step_ragged", "ragged_paged_attention"}
HOT_PATH_MODULE_PREFIXES = ("kubeflow_tpu/models/", "kubeflow_tpu/ops/")
HOT_PATH_DEPTH = 2

# Local names bound from calls matching this pattern are treated as
# device arrays (jnp./jax. calls are recognized structurally; this covers
# the repo's jitted step-callable naming: _cb_step, _paged_step, ...).
DEVICE_PRODUCER_RE = re.compile(r"^_?(cb_\w+|\w*_step|\w*step_ragged)$")

# Naming convention: a local assigned to `host_*` marks a *deliberate*
# device->host readback (the one per-step sync the batchers budget for).
HOST_READBACK_PREFIX = "host_"

# -- metric/stats parity (rule metric-stats-parity) --------------------------

# Serving, engine, gateway, autoscaler, and migration metric families
# must stay visible in the servers' JSON /stats payload; the STATS_PARITY
# table in metrics/metrics.py maps each family to the /stats key that
# surfaces it (gateway/autoscaler families surface under the gateway's
# own /stats; migration families under the orchestrator's stats block).
STATS_PARITY_FAMILY_RE = re.compile(
    r"^tpu_(serving|engine|gateway|autoscaler|migration)_[a-z0-9_]+$"
)

# Where /stats payloads are assembled: every STATS_PARITY value must
# appear as a string literal in one of these modules.
STATS_SURFACE_MODULES = (
    "kubeflow_tpu/models/server.py",
    "kubeflow_tpu/models/gateway.py",
    "kubeflow_tpu/models/autoscaler.py",
    "kubeflow_tpu/runtime/migration.py",
)
