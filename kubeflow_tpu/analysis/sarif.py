"""kftpu-lint SARIF 2.1.0 output.

One run, one driver, one result per finding. Suppressed findings are
included with a SARIF ``suppressions`` entry (kind ``inSource``) so
viewers show the justification instead of hiding the history; baselined
findings carry ``baselineState: unchanged`` and gating ones ``new``.
"""

from __future__ import annotations

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA_URI = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)
_INFO_URI = "https://github.com/opendatahub-io/kubeflow"


def _rule_descriptor(rule) -> dict:
    out = {
        "id": rule.id,
        "shortDescription": {"text": " ".join(rule.description.split())},
    }
    props = {}
    incidents = getattr(rule, "incidents", ())
    if incidents:
        props["incidents"] = list(incidents)
    docs = getattr(rule, "docs", "")
    if docs:
        props["docs"] = docs
    if props:
        out["properties"] = props
    return out


def report_to_sarif(report, rules) -> dict:
    """Render a Report (engine.Report) as a SARIF 2.1.0 log dict."""
    descriptors = [_rule_descriptor(rule) for rule in rules]
    descriptors.append(
        {
            "id": "parse-error",
            "shortDescription": {
                "text": "File could not be parsed as Python (engine-emitted)."
            },
        }
    )
    results = []
    for finding in report.findings:
        result = {
            "ruleId": finding.rule,
            "level": "warning" if finding.suppressed else "error",
            "message": {"text": finding.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": finding.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {
                            "startLine": max(finding.line, 1),
                            "startColumn": finding.col + 1,
                        },
                    }
                }
            ],
        }
        if finding.suppressed:
            result["suppressions"] = [
                {
                    "kind": "inSource",
                    "justification": finding.justification,
                }
            ]
        elif getattr(finding, "baselined", False):
            result["baselineState"] = "unchanged"
        else:
            result["baselineState"] = "new"
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA_URI,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "kftpu-lint",
                        "informationUri": _INFO_URI,
                        "rules": descriptors,
                    }
                },
                "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
                "results": results,
            }
        ],
    }
