"""kftpu-lint concurrency rules: the interprocedural family.

Built on callgraph.CallGraph plus a repo-wide **lock model**:

- every ``self.x = threading.Lock()/RLock()/Condition()/Semaphore()``
  attribute and every module-level lock, with ``Condition(self._lock)``
  aliased to the lock it wraps (waiting on the condition IS holding the
  lock);
- per-function scans recording, for each call site and each attribute
  access, the **lock-set held** at that point (``with <lock>:`` regions
  only — bare ``acquire()/release()`` pairs are deliberately untracked,
  because pairing them textually is guesswork; the repo's bounded
  ``acquire(timeout=)`` idiom stays invisible and that is the honest
  answer);
- lock-sets propagated over the call graph, bounded by
  config.LOCK_PROPAGATION_DEPTH, carrying witness paths.

Three rules ship on top:

- ``kftpu-lock-order-cycle`` — a cycle in the fleet-wide
  lock-acquisition-order graph, reported with a witness acquisition path
  for every edge on the cycle (PR 3's deadlock was exactly an order
  inversion the single-function rules could not see);
- ``kftpu-lock-held-await`` — a lock held across a call-graph-reachable
  blocking call (HTTP, queue ops, unbounded wait, subprocess, the k8s
  warm-slice claim walk). Depth >= 1 only: the depth-0 case is
  lock-held-blocking-call's single-function territory;
- ``kftpu-unguarded-shared-write`` — an attribute of a lock-owning class
  written from >= 2 entry paths (Thread targets, signal handlers, HTTP
  ``do_*`` methods, the loop-method conventions) with no common lock
  across the write sites (PR 11's stream-accounting race).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from kubeflow_tpu.analysis import config
from kubeflow_tpu.analysis.callgraph import (
    FunctionNode,
    direct_nodes,
    is_lockish_name,
)
from kubeflow_tpu.analysis.core import (
    Finding,
    SourceModule,
    dotted_parts,
    resolved_callee,
)

_LOCK_CONSTRUCTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
}

_DUNDER_INIT = {"__init__", "__post_init__", "__new__", "__enter__"}


def _lock_constructor(mod: SourceModule, expr: ast.AST) -> Optional[tuple]:
    """(kind, wrapped_expr|None) when expr constructs a threading
    primitive; wrapped_expr is Condition's first positional arg."""
    if not isinstance(expr, ast.Call):
        return None
    callee = resolved_callee(mod, expr)
    kind = _LOCK_CONSTRUCTORS.get(callee or "")
    if kind is None:
        return None
    wrapped = expr.args[0] if (kind == "Condition" and expr.args) else None
    return kind, wrapped


class LockModel:
    """Every lock the repo declares, plus helpers to resolve a
    ``with <expr>:`` context expression to a canonical lock id.

    Lock ids: ``Class.attr`` for instance locks, ``module:NAME`` for
    module-level locks, ``~leaf`` for lockish-named expressions the model
    cannot resolve (tracked as held, excluded from the order graph — an
    anonymous id colliding across unrelated locks would invent cycles).
    """

    def __init__(self, graph):
        self.graph = graph
        self.kinds: dict = {}  # lock id -> Lock/RLock/Condition/Semaphore
        self.class_locks: dict = {}  # class name -> {attr -> lock id}
        self.module_locks: dict = {}  # mod name -> {var -> lock id}
        self._scans: dict = {}  # FunctionNode.key -> _Scan
        self._build()

    def _build(self) -> None:
        for infos in self.graph.classes.values():
            for info in infos:
                table = self.class_locks.setdefault(info.name, {})
                # Two passes so Condition(self._lock) can alias a lock
                # assigned later in the same __init__.
                raw: list = []
                for method in info.methods.values():
                    for node in direct_nodes(method.node.body):
                        if not isinstance(node, ast.Assign):
                            continue
                        made = _lock_constructor(method.mod, node.value)
                        if made is None:
                            continue
                        for target in node.targets:
                            if (
                                isinstance(target, ast.Attribute)
                                and isinstance(target.value, ast.Name)
                                and target.value.id == "self"
                            ):
                                raw.append((target.attr, made))
                for attr, (kind, _wrapped) in raw:
                    if kind != "Condition":
                        lock_id = f"{info.name}.{attr}"
                        table[attr] = lock_id
                        self.kinds[lock_id] = kind
                for attr, (kind, wrapped) in raw:
                    if kind != "Condition":
                        continue
                    parts = dotted_parts(wrapped) if wrapped is not None else None
                    if (
                        parts
                        and len(parts) == 2
                        and parts[0] == "self"
                        and parts[1] in table
                    ):
                        table[attr] = table[parts[1]]  # alias to wrapped lock
                    else:
                        lock_id = f"{info.name}.{attr}"
                        table[attr] = lock_id
                        self.kinds[lock_id] = kind
        for mod in self.graph.index.modules.values():
            if mod.tree is None:
                continue
            table = self.module_locks.setdefault(mod.name, {})
            for node in mod.tree.body:
                if not isinstance(node, ast.Assign):
                    continue
                made = _lock_constructor(mod, node.value)
                if made is None:
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        lock_id = f"{mod.name}:{target.id}"
                        table[target.id] = lock_id
                        self.kinds[lock_id] = made[0]

    # -- resolution ----------------------------------------------------------

    def resolve_lock_expr(self, fn: FunctionNode, expr: ast.AST) -> Optional[str]:
        parts = dotted_parts(expr)
        if parts is None:
            return None
        leaf = parts[-1]
        if len(parts) == 2 and parts[0] == "self" and fn.cls:
            table = self.class_locks.get(fn.cls, {})
            if leaf in table:
                return table[leaf]
        if len(parts) == 1:
            table = self.module_locks.get(fn.mod.name, {})
            if leaf in table:
                return table[leaf]
        if len(parts) == 3 and parts[0] == "self" and fn.cls:
            # self.collab._lock through the learned attribute types.
            for info in self.graph.classes.get(fn.cls, []):
                if info.mod is not fn.mod:
                    continue
                for type_name in info.attr_types.get(parts[1], set()):
                    lock_id = self.class_locks.get(type_name, {}).get(leaf)
                    if lock_id:
                        return lock_id
        if is_lockish_name(leaf):
            return f"~{leaf}"  # held, but anonymous: no order edges
        return None

    @staticmethod
    def is_anonymous(lock_id: str) -> bool:
        return lock_id.startswith("~")

    def scan(self, fn: FunctionNode) -> "_Scan":
        cached = self._scans.get(fn.key)
        if cached is None:
            cached = _scan_function(self, fn)
            self._scans[fn.key] = cached
        return cached


@dataclass
class _Scan:
    """One function's lock-relevant events, each with the locally held
    lock-set (with-regions inside this function only)."""

    calls: list = field(default_factory=list)  # (ast.Call, frozenset)
    acquisitions: list = field(default_factory=list)  # (With, id, frozenset before)
    writes: list = field(default_factory=list)  # (attr, node, frozenset, is_aug)


def _scan_function(model: LockModel, fn: FunctionNode) -> _Scan:
    out = _Scan()

    def visit(node: ast.AST, held: frozenset) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                visit(item.context_expr, held)
                lock_id = model.resolve_lock_expr(fn, item.context_expr)
                if lock_id is not None:
                    out.acquisitions.append((node, lock_id, frozenset(held)))
                    inner.add(lock_id)
            for child in node.body:
                visit(child, frozenset(inner))
            return
        if isinstance(node, ast.Call):
            out.calls.append((node, held))
        elif isinstance(node, ast.Assign):
            for target in node.targets:
                elts = target.elts if isinstance(target, ast.Tuple) else [target]
                for elt in elts:
                    if (
                        isinstance(elt, ast.Attribute)
                        and isinstance(elt.value, ast.Name)
                        and elt.value.id == "self"
                    ):
                        out.writes.append((elt.attr, node, held, False))
        elif isinstance(node, ast.AugAssign):
            target = node.target
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                out.writes.append((target.attr, node, held, True))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.node.body:
        visit(stmt, frozenset())
    return out


# -- blocking classification for kftpu-lock-held-await -----------------------


def _kwarg_names(call: ast.Call) -> set:
    return {kw.arg for kw in call.keywords if kw.arg}


def _queueish_receiver(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    parts = dotted_parts(call.func.value)
    if not parts:
        return False
    low = parts[-1].lower()
    return low == "q" or "queue" in low


def _await_reason(mod: SourceModule, call: ast.Call) -> Optional[str]:
    """Why this direct call can block for await purposes, or None."""
    callee = resolved_callee(mod, call) or ""
    if callee in config.BLOCKING_AWAIT_CALLEES:
        return config.BLOCKING_AWAIT_CALLEES[callee]
    leaf = callee.rsplit(".", 1)[-1] if callee else ""
    if leaf in ("HTTPConnection", "HTTPSConnection"):
        return "HTTP connection"
    if leaf == "urlopen":
        return "network I/O (urlopen)"
    if leaf in config.BLOCKING_AWAIT_FUNCTIONS:
        return config.BLOCKING_AWAIT_FUNCTIONS[leaf]
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    bare = not call.args and not call.keywords
    if attr in ("wait", "join") and bare:
        if not isinstance(call.func.value, ast.Constant):
            return f"unbounded {attr}()"
    if attr in ("put", "get") and _queueish_receiver(call):
        kwargs = _kwarg_names(call)
        if "timeout" in kwargs:
            return None
        for kw in call.keywords:
            if (
                kw.arg == "block"
                and isinstance(kw.value, ast.Constant)
                and not kw.value.value
            ):
                return None
        return f"blocking queue .{attr}()"
    return None


# -- the rules ---------------------------------------------------------------


class ConcurrencyRule:
    """Base: lazily builds (and caches on the index) the shared LockModel."""

    id = ""
    description = ""
    incidents: tuple = ()
    docs = ""

    def check_module(self, mod: SourceModule, index) -> list:
        return []

    def check_repo(self, index, checked: dict) -> list:
        return []

    @staticmethod
    def model(index) -> LockModel:
        cached = getattr(index, "_lock_model", None)
        if cached is None:
            cached = LockModel(index.callgraph())
            index._lock_model = cached
        return cached


def _call_targets(graph, fn: FunctionNode) -> dict:
    """id(ast.Call) -> [FunctionNode] for a function's resolved edges."""
    out: dict = {}
    for call, target in graph.edges.get(fn.key, []):
        out.setdefault(id(call), []).append(target)
    return out


class LockOrderCycle(ConcurrencyRule):
    id = "kftpu-lock-order-cycle"
    description = (
        "Two code paths acquire the same locks in opposite orders "
        "(directly or through calls): threads interleaving the paths "
        "deadlock. The fleet's documented order is autoscaler lock -> "
        "gateway.stats -> gateway._lock and never the reverse; this rule "
        "makes that invariant mechanical. Reported with a witness "
        "acquisition path for every edge on the cycle."
    )
    incidents = (
        "PR 3: emergency-save deadlock — a signal handler re-entered a "
        "queue mutex its own interrupted thread held",
    )
    docs = "ARCHITECTURE.md#static-analysis — lock-order graph"

    def check_repo(self, index, checked: dict) -> list:
        model = self.model(index)
        graph = model.graph
        # (held -> acquired) -> witness dict
        edges: dict = {}

        def record(held_id, acq_id, witness):
            if held_id == acq_id:
                return  # RLock re-entry / same lock: not an order edge
            if model.is_anonymous(held_id) or model.is_anonymous(acq_id):
                return
            edges.setdefault((held_id, acq_id), witness)

        for fn in graph.functions.values():
            scan = model.scan(fn)
            for with_node, acq_id, held_before in scan.acquisitions:
                for held_id in held_before:
                    record(
                        held_id,
                        acq_id,
                        {
                            "fn": fn,
                            "node": with_node,
                            "path": (),
                            "holder": fn,
                        },
                    )
            targets = _call_targets(graph, fn)
            for call, held in scan.calls:
                if not held or id(call) not in targets:
                    continue
                self._propagate(
                    model, graph, fn, call, held, targets[id(call)], record
                )

        findings = []
        adj: dict = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
        reported: set = set()
        for (a, b) in sorted(edges):
            cycle = self._path(adj, b, a)
            if cycle is None:
                continue
            nodes = frozenset([a] + cycle)
            if nodes in reported:
                continue
            reported.add(nodes)
            ring = [a, b] + cycle[1:]  # a -> b -> ... -> a
            legs = []
            for i in range(len(ring) - 1):
                witness = edges.get((ring[i], ring[i + 1]))
                if witness is None:
                    continue
                legs.append(self._render_witness(ring[i], ring[i + 1], witness))
            first = edges[(a, b)]
            # Report where the inversion STARTS: the holder's call site
            # (for a propagated edge) or the nested with (direct) — the
            # place already holding lock a when lock b gets taken.
            site_fn = first["holder"]
            site_node = (
                first["path"][0][1] if first["path"] else first["node"]
            )
            rel = site_fn.mod.rel
            if rel not in checked:
                continue
            order = " -> ".join(ring)
            findings.append(
                Finding(
                    self.id,
                    rel,
                    site_node.lineno,
                    site_node.col_offset,
                    f"lock-order cycle {order}: "
                    + "; ".join(legs)
                    + " — threads interleaving these paths deadlock; pick "
                    "one fleet-wide acquisition order (see "
                    "ARCHITECTURE.md#static-analysis)",
                )
            )
        return findings

    def _propagate(self, model, graph, origin, call, held, targets, record):
        seen = set()
        frontier = [(t, ((origin, call),)) for t in targets]
        while frontier:
            fn, path = frontier.pop(0)
            if fn.key in seen or len(path) > config.LOCK_PROPAGATION_DEPTH:
                continue
            seen.add(fn.key)
            scan = model.scan(fn)
            for with_node, acq_id, held_before in scan.acquisitions:
                for held_id in held | held_before:
                    record(
                        held_id,
                        acq_id,
                        {"fn": fn, "node": with_node, "path": path,
                         "holder": origin},
                    )
            fn_targets = _call_targets(graph, fn)
            for inner_call, inner_held in scan.calls:
                for target in fn_targets.get(id(inner_call), []):
                    frontier.append((target, path + ((fn, inner_call),)))
                if inner_held:
                    # locks taken deeper are handled when that frame is
                    # visited; nothing extra to do here.
                    pass

    @staticmethod
    def _path(adj, src, dst):
        """Shortest node path src..dst through adj, or None."""
        frontier = [(src, [src])]
        seen = {src}
        while frontier:
            node, path = frontier.pop(0)
            if node == dst:
                return path
            for nxt in sorted(adj.get(node, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append((nxt, path + [nxt]))
        return None

    @staticmethod
    def _render_witness(held_id, acq_id, witness) -> str:
        where = f"{witness['fn'].mod.rel}:{witness['node'].lineno}"
        if witness["path"]:
            hops = " -> ".join(
                f"{caller.qualname} ({caller.mod.rel}:{call.lineno})"
                for caller, call in witness["path"]
            )
            via = f" via {hops} -> {witness['fn'].qualname}"
        else:
            via = f" in {witness['fn'].qualname}"
        return (
            f"holding '{held_id}', acquires '{acq_id}' at {where}{via}"
        )


class LockHeldAwait(ConcurrencyRule):
    id = "kftpu-lock-held-await"
    description = (
        "A lock is held across a call that can block — HTTP, a blocking "
        "queue op, an unbounded wait()/join(), subprocess, or the k8s "
        "warm-slice claim walk — reached through the call graph (depth "
        ">= 1; the single-function case is lock-held-blocking-call). "
        "Every thread needing the lock stalls for the full round trip: "
        "do the slow work outside the critical section and re-take the "
        "lock to publish the result."
    )
    incidents = (
        "PR 3: emergency-save deadlock — blocking work reached from a "
        "context that could not afford to wait",
    )
    docs = "CONTRIBUTING.md#modeling-locks-and-thread-entry-points"

    def check_repo(self, index, checked: dict) -> list:
        model = self.model(index)
        graph = model.graph
        findings = []
        for fn in graph.functions.values():
            if fn.mod.rel not in checked:
                continue
            scan = model.scan(fn)
            targets = _call_targets(graph, fn)
            reported: set = set()
            for call, held in scan.calls:
                if not held or id(call) not in targets:
                    continue
                locks = ", ".join(sorted(h.lstrip("~") for h in held))
                frontier = [(t, ((fn, call),)) for t in targets[id(call)]]
                seen: set = set()
                while frontier:
                    node, path = frontier.pop(0)
                    if node.key in seen or len(path) > config.LOCK_AWAIT_DEPTH:
                        continue
                    seen.add(node.key)
                    node_scan = model.scan(node)
                    for inner_call, _inner_held in node_scan.calls:
                        reason = _await_reason(node.mod, inner_call)
                        if reason is None:
                            continue
                        key = (call.lineno, node.mod.rel, inner_call.lineno)
                        if key in reported:
                            continue
                        reported.add(key)
                        hops = " -> ".join(
                            [
                                f"{c.qualname} ({c.mod.rel}:{cl.lineno})"
                                for c, cl in path
                            ]
                            + [node.qualname]
                        )
                        findings.append(
                            Finding(
                                self.id,
                                fn.mod.rel,
                                call.lineno,
                                call.col_offset,
                                f"'{locks}' held across {reason} at "
                                f"{node.mod.rel}:{inner_call.lineno} "
                                f"(path: {hops}); move the blocking work "
                                "outside the critical section and "
                                "re-take the lock to publish the result",
                            )
                        )
                    node_targets = _call_targets(graph, node)
                    for inner_call, _h in node_scan.calls:
                        for target in node_targets.get(id(inner_call), []):
                            frontier.append(
                                (target, path + ((node, inner_call),))
                            )
        return findings


class UnguardedSharedWrite(ConcurrencyRule):
    id = "kftpu-unguarded-shared-write"
    description = (
        "An attribute of a lock-owning class is written from >= 2 entry "
        "paths — Thread(target=...), a signal handler, an HTTP do_* "
        "method, or a loop-method convention (run/tick/_drive/_drain) — "
        "and the write sites share no common lock (one path writes "
        "unlocked, or the paths use different locks). Lost updates and "
        "torn multi-field state follow. __init__ writes and plain "
        "never-locked flag stores are exempt; fire needs an augmented "
        "write or an inconsistently-guarded write."
    )
    incidents = (
        "PR 11: stream-accounting race — a client hanging up right "
        "after [DONE] was miscounted as a cancel because two threads "
        "updated the tally through different guards",
    )
    docs = "CONTRIBUTING.md#modeling-locks-and-thread-entry-points"

    def check_repo(self, index, checked: dict) -> list:
        model = self.model(index)
        graph = model.graph
        findings = []
        for infos in graph.classes.values():
            for info in infos:
                if info.mod.rel not in checked:
                    continue
                lock_attrs = model.class_locks.get(info.name, {})
                if not lock_attrs:
                    continue
                findings.extend(self._check_class(model, graph, info, lock_attrs))
        return findings

    def _entry_roots(self, graph, info) -> dict:
        """method name -> entry kind, for structurally-detected entries."""
        entries: dict = {}
        httpish = any("HTTPRequestHandler" in b for b in info.bases)
        for name in info.methods:
            if name in config.THREAD_ENTRY_METHODS:
                entries[name] = "loop method"
            if name.startswith("do_") and httpish:
                entries[name] = "HTTP handler"
        for method in info.methods.values():
            for node in direct_nodes(method.node.body):
                if not isinstance(node, ast.Call):
                    continue
                callee = resolved_callee(method.mod, node) or ""
                leaf = callee.rsplit(".", 1)[-1]
                target_expr = None
                if leaf == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target_expr = kw.value
                elif callee == "signal.signal" and len(node.args) >= 2:
                    target_expr = node.args[1]
                if target_expr is None:
                    continue
                parts = dotted_parts(target_expr)
                if parts and len(parts) == 2 and parts[0] == "self":
                    if parts[1] in info.methods:
                        kind = (
                            "Thread target" if leaf == "Thread"
                            else "signal handler"
                        )
                        entries[parts[1]] = kind
        return entries

    def _check_class(self, model, graph, info, lock_attrs) -> list:
        entries = self._entry_roots(graph, info)
        called_internally: set = set()
        same_class_targets: dict = {}  # method name -> {id(call) -> [names]}
        for name, method in info.methods.items():
            per_call: dict = {}
            for call, target in graph.edges.get(method.key, []):
                if target.cls == info.name and target.mod is info.mod:
                    per_call.setdefault(id(call), []).append(target.name)
                    called_internally.add(target.name)
            same_class_targets[name] = per_call

        roots = {
            name
            for name in info.methods
            if name not in called_internally or name in entries
        }
        # attr -> list of {root, method, node, held, aug}
        accesses: dict = {}
        for root in sorted(roots):
            if root in _DUNDER_INIT:
                continue
            frontier = [(root, frozenset())]
            seen: set = set()
            while frontier:
                name, held = frontier.pop(0)
                state = (name, held)
                if state in seen or name in _DUNDER_INIT:
                    continue
                seen.add(state)
                method = info.methods[name]
                scan = model.scan(method)
                for attr, node, local_held, is_aug in scan.writes:
                    if attr in lock_attrs or attr.startswith("__"):
                        continue
                    accesses.setdefault(attr, []).append(
                        {
                            "root": root,
                            "method": name,
                            "node": node,
                            "held": held | local_held,
                            "aug": is_aug,
                        }
                    )
                per_call = same_class_targets[name]
                for call, local_held in scan.calls:
                    for target_name in per_call.get(id(call), []):
                        frontier.append((target_name, held | local_held))

        findings = []
        for attr in sorted(accesses):
            records = accesses[attr]
            writer_roots = {r["root"] for r in records}
            if len(writer_roots) < 2:
                continue
            if not any(root in entries for root in writer_roots):
                continue
            held_sets = [set(r["held"]) for r in records]
            common = set.intersection(*held_sets) if held_sets else set()
            if common:
                continue
            some_locked = any(r["held"] for r in records)
            some_aug = any(r["aug"] for r in records)
            if not (some_locked or some_aug):
                continue  # plain never-locked flag stores stay exempt
            worst = next(
                (r for r in records if not r["held"]), records[0]
            )
            contexts = []
            for root in sorted(writer_roots):
                root_records = [r for r in records if r["root"] == root]
                locks = sorted(
                    {h.lstrip("~") for r in root_records for h in r["held"]}
                )
                kind = entries.get(root, "external caller")
                guard = f"under {', '.join(locks)}" if locks else "unlocked"
                lines = sorted({r["node"].lineno for r in root_records})
                contexts.append(
                    f"{root} [{kind}] writes {guard} "
                    f"(line {', '.join(str(n) for n in lines)})"
                )
            findings.append(
                Finding(
                    self.id,
                    info.mod.rel,
                    worst["node"].lineno,
                    worst["node"].col_offset,
                    f"self.{attr} of {info.name} is written from "
                    f"{len(writer_roots)} entry paths with no common "
                    f"lock: " + "; ".join(contexts) + " — guard every "
                    f"mutation of {attr} with the same lock",
                )
            )
        return findings


CONCURRENCY_RULES = [LockOrderCycle(), LockHeldAwait(), UnguardedSharedWrite()]
