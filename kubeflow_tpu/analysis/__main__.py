"""CLI: python -m kubeflow_tpu.analysis [paths ...] [--format json]
       [--diff RANGE] [--sarif] [--baseline FILE] [--update-baseline].
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from kubeflow_tpu.analysis import baseline as baseline_mod
from kubeflow_tpu.analysis.engine import run_analysis
from kubeflow_tpu.analysis.rules import ALL_RULES
from kubeflow_tpu.analysis.sarif import report_to_sarif


def _print_rules() -> None:
    for rule in ALL_RULES:
        print(f"{rule.id}\n    {' '.join(rule.description.split())}")
        for incident in getattr(rule, "incidents", ()):
            print(f"    incident: {' '.join(incident.split())}")
        docs = getattr(rule, "docs", "")
        if docs:
            print(f"    docs: {docs}")
    print("parse-error\n    File could not be parsed as Python (engine-emitted).")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubeflow_tpu.analysis",
        description=(
            "kftpu-lint: AST analysis with cross-module contract and "
            "interprocedural concurrency checks. Exits 1 when gating "
            "(unsuppressed, unbaselined, in-diff) findings exist."
        ),
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to check (default: kubeflow_tpu/)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json includes suppressed findings with flags)",
    )
    parser.add_argument(
        "--include-suppressed", action="store_true",
        help="text mode: also print suppressed findings",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print every rule id, description, incident citations and "
             "docs links, then exit",
    )
    parser.add_argument(
        "--baseline", metavar="FILE", default=None,
        help="baseline file to gate against (default: the checked-in "
             "kubeflow_tpu/analysis/baseline.json)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: every unsuppressed finding gates",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="write the current unsuppressed findings to the baseline "
             "file and exit 0 (use via `make lint-baseline`)",
    )
    parser.add_argument(
        "--diff", metavar="RANGE", default=None,
        help="git range (e.g. origin/main..HEAD); findings outside the "
             "range's changed lines do not gate",
    )
    parser.add_argument(
        "--sarif", action="store_true",
        help="emit SARIF 2.1.0 JSON on stdout (overrides --format)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        _print_rules()
        return 0

    baseline_path = Path(args.baseline) if args.baseline else None
    report = run_analysis(
        paths=args.paths or None,
        baseline_path=False if args.no_baseline else baseline_path,
        diff_range=args.diff,
    )

    if args.update_baseline:
        target = baseline_path or baseline_mod.BASELINE_PATH
        count = baseline_mod.write_baseline(report, report.index, target)
        print(f"kftpu-lint: baseline written to {target} ({count} entries)")
        return 0

    if args.sarif:
        print(json.dumps(report_to_sarif(report, ALL_RULES), indent=2))
    elif args.format == "json":
        print(json.dumps(report.as_dict(), indent=2, sort_keys=True))
    else:
        print(report.render_text(include_suppressed=args.include_suppressed))
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
