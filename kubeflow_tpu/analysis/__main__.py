"""CLI: python -m kubeflow_tpu.analysis [paths ...] [--format json]."""

from __future__ import annotations

import argparse
import json
import sys

from kubeflow_tpu.analysis.engine import run_analysis
from kubeflow_tpu.analysis.rules import ALL_RULES


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubeflow_tpu.analysis",
        description=(
            "kftpu-lint: AST analysis with cross-module contract checks. "
            "Exits 1 when unsuppressed findings exist."
        ),
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to check (default: kubeflow_tpu/)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json includes suppressed findings with flags)",
    )
    parser.add_argument(
        "--include-suppressed", action="store_true",
        help="text mode: also print suppressed findings",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print every rule id and description, then exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id}\n    {' '.join(rule.description.split())}")
        print("parse-error\n    File could not be parsed as Python (engine-emitted).")
        return 0

    report = run_analysis(paths=args.paths or None)
    if args.format == "json":
        print(json.dumps(report.as_dict(), indent=2, sort_keys=True))
    else:
        print(report.render_text(include_suppressed=args.include_suppressed))
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
