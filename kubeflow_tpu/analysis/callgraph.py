"""kftpu-lint call graph: the interprocedural substrate every v2 rule rides.

PR 4's rules were single-module pattern matchers; the one interprocedural
walker (BlockingInSignalHandler's worklist over same-module defs) was
private to that rule and could not cross files. This module extracts and
generalizes it:

- a repo-wide **class map** (classes, their bases, their methods, and the
  attribute types learned from ``self.x = SomeClass(...)`` assignments in
  any method, plus the declared hints in config.ATTR_TYPE_HINTS for
  attributes that are only ever assigned from constructor parameters);
- **call-site resolution**: bare names through the module's def table and
  import table, ``self.m()``/``cls.m()`` through the class map with base
  walking, ``self.attr.m()`` through the learned attribute types, dotted
  names through imports, and a *bounded* dynamic-dispatch fallback (an
  unqualified ``obj.m()`` resolves only when at most
  config.DISPATCH_CAP classes in the repo define ``m`` and ``m`` is not a
  ubiquitous name) — unresolvable calls contribute no edges rather than
  guesses;
- **bounded-depth reachability** with full witness paths, so rules can
  report *how* a handler reaches a blocking call, not just that it does.

Lock-protocol methods (acquire/release/wait/...) never resolve through
the dynamic-dispatch fallback, and receivers whose name looks like a
synchronization primitive never produce edges at all: a spurious edge
from ``q.all_tasks_done.acquire()`` into some repo class's ``acquire``
would poison every concurrency rule downstream.

Everything stays pure ``ast``: no analyzed code is imported.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterator, Optional

from kubeflow_tpu.analysis import config
from kubeflow_tpu.analysis.core import SourceModule, dotted_parts

_LOCKISH_RE = re.compile(r"lock|cond|sem|mutex|event|busy", re.IGNORECASE)


def is_lockish_name(name: str) -> bool:
    """Does this identifier look like a synchronization primitive?"""
    return bool(_LOCKISH_RE.search(name))


def direct_nodes(stmts) -> Iterator[ast.AST]:
    """Walk statements without descending into nested def/lambda bodies —
    nested functions only run when called, and calls are followed
    explicitly by the reachability walker."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


@dataclass
class FunctionNode:
    """One function or method definition in the repo."""

    key: str  # unique: "<rel>::<Class.>name[#lineno]"
    mod: SourceModule
    cls: Optional[str]  # owning class name, None for module-level defs
    name: str
    node: ast.AST  # FunctionDef / AsyncFunctionDef

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name

    def where(self) -> str:
        return f"{self.mod.rel}:{self.node.lineno}"


@dataclass
class ClassInfo:
    name: str
    mod: SourceModule
    node: ast.ClassDef
    bases: list = field(default_factory=list)  # base-class leaf names
    methods: dict = field(default_factory=dict)  # name -> FunctionNode
    # attribute -> set of class names learned from `self.attr = Cls(...)`
    attr_types: dict = field(default_factory=dict)


class CallGraph:
    """Repo-wide call graph over a RepoIndex's modules."""

    def __init__(self, index):
        self.index = index
        self.functions: dict = {}  # key -> FunctionNode
        self.classes: dict = {}  # class name -> [ClassInfo] (collisions kept)
        self.class_of_node: dict = {}  # id(ClassDef) -> ClassInfo
        self.module_defs: dict = {}  # mod.name -> {fn name -> [FunctionNode]}
        self.edges: dict = {}  # caller key -> [(ast.Call, FunctionNode)]
        self._fn_for_def: dict = {}  # id(def node) -> FunctionNode
        self._build()

    # -- construction --------------------------------------------------------

    def _build(self) -> None:
        for mod in self.index.modules.values():
            if mod.tree is None:
                continue
            self._collect_module(mod)
        for infos in self.classes.values():
            for info in infos:
                self._learn_attr_types(info)
        for fn in self.functions.values():
            self.edges[fn.key] = self._resolve_edges(fn)

    def _collect_module(self, mod: SourceModule) -> None:
        defs: dict = self.module_defs.setdefault(mod.name, {})

        def add_fn(node, cls: Optional[str]) -> FunctionNode:
            key = f"{mod.rel}::{cls + '.' if cls else ''}{node.name}#{node.lineno}"
            fn = FunctionNode(key, mod, cls, node.name, node)
            self.functions[key] = fn
            self._fn_for_def[id(node)] = fn
            defs.setdefault(node.name, []).append(fn)
            return fn

        for node in mod.walk():
            if isinstance(node, ast.ClassDef):
                info = ClassInfo(node.name, mod, node)
                for base in node.bases:
                    parts = dotted_parts(base)
                    if parts:
                        info.bases.append(parts[-1])
                for child in node.body:
                    if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        info.methods[child.name] = add_fn(child, node.name)
                self.classes.setdefault(node.name, []).append(info)
                self.class_of_node[id(node)] = info
        # Defs not directly under a class body (module level and nested).
        for node in mod.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) not in self._fn_for_def:
                    add_fn(node, None)

    def _learn_attr_types(self, info: ClassInfo) -> None:
        for hint_key, (type_name, _reason) in config.ATTR_TYPE_HINTS.items():
            cls_name, attr = hint_key
            if cls_name == info.name:
                info.attr_types.setdefault(attr, set()).add(type_name)
        for method in info.methods.values():
            for node in direct_nodes(method.node.body):
                if not isinstance(node, ast.Assign):
                    continue
                for target in node.targets:
                    if not (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                    ):
                        continue
                    for cls_name in self._constructed_classes(
                        method.mod, node.value
                    ):
                        info.attr_types.setdefault(target.attr, set()).add(
                            cls_name
                        )

    def _constructed_classes(self, mod: SourceModule, expr: ast.AST) -> set:
        """Class names constructed anywhere in expr (IfExp/BoolOp branches
        included) that resolve to classes known to the repo."""
        out: set = set()
        candidates = [expr]
        if isinstance(expr, ast.IfExp):
            candidates = [expr.body, expr.orelse]
        elif isinstance(expr, ast.BoolOp):
            candidates = list(expr.values)
        for cand in candidates:
            if not isinstance(cand, ast.Call):
                continue
            parts = dotted_parts(cand.func)
            if not parts:
                continue
            leaf = parts[-1]
            if leaf in self.classes:
                out.add(leaf)
        return out

    # -- resolution ----------------------------------------------------------

    def fn_for(self, def_node: ast.AST) -> Optional[FunctionNode]:
        return self._fn_for_def.get(id(def_node))

    def class_method(
        self, info: ClassInfo, name: str, _seen: Optional[set] = None
    ) -> Optional[FunctionNode]:
        """Look up a method on a class, walking base classes by name."""
        if name in info.methods:
            return info.methods[name]
        seen = _seen if _seen is not None else set()
        seen.add(info.name)
        for base in info.bases:
            if base in seen:
                continue
            for base_info in self.classes.get(base, []):
                found = self.class_method(base_info, name, seen)
                if found is not None:
                    return found
        return None

    def _dispatch(self, method: str) -> list:
        """Bounded dynamic-dispatch fallback for an untyped receiver."""
        if method in config.DISPATCH_SKIP_NAMES:
            return []
        if method in config.LOCK_PROTOCOL_METHODS:
            return []
        candidates = [
            info.methods[method]
            for infos in self.classes.values()
            for info in infos
            if method in info.methods
        ]
        if 1 <= len(candidates) <= config.DISPATCH_CAP:
            return candidates
        return []

    def _lookup_dotted(self, dotted: str) -> list:
        parts = dotted.split(".")
        if len(parts) < 2:
            return []
        owner, leaf = ".".join(parts[:-1]), parts[-1]
        mod = self.index.modules.get(owner)
        if mod is None:
            return []
        for fn in self.module_defs.get(mod.name, {}).get(leaf, []):
            if fn.cls is None:
                return [fn]
        # Imported class constructed directly: edge into its __init__.
        for info in self.classes.get(leaf, []):
            if info.mod is mod and "__init__" in info.methods:
                return [info.methods["__init__"]]
        return []

    def resolve_call(self, caller: FunctionNode, call: ast.Call) -> list:
        parts = dotted_parts(call.func)
        if parts is None:
            return []
        mod = caller.mod
        if len(parts) == 1:
            name = parts[0]
            local = [
                fn
                for fn in self.module_defs.get(mod.name, {}).get(name, [])
                if fn.cls is None
            ]
            if local:
                return local
            if name in self.classes:
                for info in self.classes[name]:
                    if "__init__" in info.methods:
                        return [info.methods["__init__"]]
                return []
            target = mod.imports.get(name)
            if target and "." in target:
                return self._lookup_dotted(target)
            return []
        leaf = parts[-1]
        receiver_leaf = parts[-2]
        if is_lockish_name(receiver_leaf):
            return []  # lock.acquire()/cond.wait() are not repo methods
        if parts[0] in ("self", "cls") and caller.cls:
            infos = [
                info
                for info in self.classes.get(caller.cls, [])
                if info.mod is caller.mod
            ]
            if len(parts) == 2 and infos:
                found = self.class_method(infos[0], leaf)
                return [found] if found else self._dispatch(leaf)
            if len(parts) == 3 and infos:
                types = infos[0].attr_types.get(parts[1], set())
                resolved = []
                for type_name in types:
                    for type_info in self.classes.get(type_name, []):
                        found = self.class_method(type_info, leaf)
                        if found:
                            resolved.append(found)
                return resolved or self._dispatch(leaf)
            return self._dispatch(leaf)
        # Dotted through the import table: module.func / pkg.mod.func.
        head = mod.imports.get(parts[0])
        if head:
            dotted = ".".join([head] + parts[1:])
            found = self._lookup_dotted(dotted)
            if found:
                return found
            if head.startswith("kubeflow_tpu"):
                return []  # repo-internal but unknown: no guessing
        return self._dispatch(leaf)

    def _resolve_edges(self, fn: FunctionNode) -> list:
        edges = []
        for node in direct_nodes(fn.node.body):
            if isinstance(node, ast.Call):
                for target in self.resolve_call(fn, node):
                    edges.append((node, target))
        return edges

    # -- reachability --------------------------------------------------------

    def reachable(
        self, start: FunctionNode, max_depth: Optional[int] = None
    ) -> Iterator[tuple]:
        """BFS from start, yielding (fn, depth, path) where path is a tuple
        of (caller FunctionNode, ast.Call) hops leading to fn. Depth 0 is
        start itself with an empty path. Recursion-safe: each function is
        visited once at its shallowest depth."""
        depth_cap = config.CALLGRAPH_MAX_DEPTH if max_depth is None else max_depth
        seen = {start.key}
        frontier = [(start, 0, ())]
        while frontier:
            fn, depth, path = frontier.pop(0)
            yield fn, depth, path
            if depth >= depth_cap:
                continue
            for call, target in self.edges.get(fn.key, []):
                if target.key in seen:
                    continue
                seen.add(target.key)
                frontier.append((target, depth + 1, path + ((fn, call),)))

    def render_path(self, path: tuple, final: FunctionNode) -> str:
        """'a (x.py:10) -> b (y.py:20) -> c' for a reachability path."""
        hops = [
            f"{caller.qualname} ({caller.mod.rel}:{call.lineno})"
            for caller, call in path
        ]
        hops.append(final.qualname)
        return " -> ".join(hops)
