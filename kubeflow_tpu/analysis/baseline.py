"""kftpu-lint baseline + diff gating.

Two mechanisms that make a new rule shippable against a mature repo
without a flag day:

- **baseline** (``analysis/baseline.json``, checked in): known findings,
  fingerprinted by (rule, path, normalized source-line text) so entries
  survive line-number drift from unrelated edits. A finding matching an
  unconsumed baseline entry is marked ``baselined`` and does not gate;
  ``make lint-baseline`` regenerates the file. The repo's standing bar is
  an **empty** baseline — the mechanism exists for rule rollout, not as a
  parking lot (a justified inline suppression is the long-term answer).

- **diff mode** (``--diff <git-range>``): findings outside the range's
  changed lines are marked ``out_of_diff`` and do not gate — PR CI gets
  "you may not add findings" even mid-rollout of a noisy rule.

Gating findings = unsuppressed - baselined - out_of_diff; the exit code
rides on that.
"""

from __future__ import annotations

import hashlib
import json
import re
import subprocess
from pathlib import Path
from typing import Optional

BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"
BASELINE_VERSION = 1

_HUNK_RE = re.compile(r"^@@ -\d+(?:,\d+)? \+(\d+)(?:,(\d+))? @@")


def fingerprint(finding, index) -> str:
    """Stable identity: rule + path + the stripped source line. Survives
    pure line-shift; a same-rule finding on an identical duplicated line
    is disambiguated by consumption order (each entry matches once)."""
    mod = index.by_rel.get(finding.path)
    line_text = ""
    if mod is not None and 0 < finding.line <= len(mod.lines):
        line_text = mod.lines[finding.line - 1].strip()
    digest = hashlib.sha1(
        f"{finding.rule}\n{finding.path}\n{line_text}".encode("utf-8")
    )
    return digest.hexdigest()[:16]


def load_baseline(path: Optional[Path] = None) -> list:
    target = Path(path) if path else BASELINE_PATH
    if not target.is_file():
        return []
    data = json.loads(target.read_text(encoding="utf-8"))
    return list(data.get("findings", []))


def apply_baseline(report, entries: list, index) -> None:
    """Mark unsuppressed findings matching an unconsumed entry."""
    unused = {}
    for entry in entries:
        key = (entry.get("rule"), entry.get("path"), entry.get("fingerprint"))
        unused[key] = unused.get(key, 0) + 1
    for finding in report.findings:
        if finding.suppressed:
            continue
        key = (finding.rule, finding.path, fingerprint(finding, index))
        if unused.get(key, 0) > 0:
            unused[key] -= 1
            finding.baselined = True


def write_baseline(report, index, path: Optional[Path] = None) -> int:
    """Snapshot every unsuppressed finding; returns the entry count."""
    entries = [
        {
            "rule": f.rule,
            "path": f.path,
            "fingerprint": fingerprint(f, index),
            "line": f.line,  # informational only; matching is by fingerprint
            "message": f.message,
        }
        for f in report.unsuppressed
    ]
    target = Path(path) if path else BASELINE_PATH
    target.write_text(
        json.dumps(
            {"version": BASELINE_VERSION, "findings": entries},
            indent=2,
            sort_keys=True,
        )
        + "\n",
        encoding="utf-8",
    )
    return len(entries)


def changed_lines(git_range: str, repo_root: Path) -> Optional[dict]:
    """rel posix path -> set of changed (new-side) line numbers for the
    range, or None when git cannot answer (not a repo, bad range)."""
    try:
        proc = subprocess.run(
            ["git", "diff", "--unified=0", "--no-color", git_range, "--", "*.py"],
            cwd=str(repo_root),
            capture_output=True,
            text=True,
            timeout=60,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    out: dict = {}
    current: Optional[str] = None
    for line in proc.stdout.splitlines():
        if line.startswith("+++ "):
            name = line[4:].strip()
            current = None if name == "/dev/null" else name.removeprefix("b/")
            continue
        m = _HUNK_RE.match(line)
        if m and current is not None:
            start = int(m.group(1))
            count = int(m.group(2)) if m.group(2) is not None else 1
            if count:
                out.setdefault(current, set()).update(
                    range(start, start + count)
                )
            else:
                # pure deletion: keep the file keyed so file-level
                # findings (line 1 parse errors etc.) still gate
                out.setdefault(current, set())
    return out


def apply_diff_filter(report, changed: dict) -> None:
    """Mark findings outside the changed lines as out_of_diff."""
    for finding in report.findings:
        if finding.suppressed:
            continue
        lines = changed.get(finding.path)
        if lines is None:
            finding.out_of_diff = True
        elif finding.line not in lines and finding.line != 1:
            # line-1 findings are file-level (parse-error); any change to
            # the file keeps them gating
            finding.out_of_diff = True
