"""kftpu-lint rules.

Two families:

- single-module concurrency/safety rules — the bug classes this repo has
  actually shipped (PR 3's emergency-save deadlock was a blocking queue
  op inside a SIGTERM handler) plus the reconcile-loop disciplines the
  controller tier depends on;
- cross-module contract rules — names that must agree across layers
  (webhook env contract <-> runtime reads, metric registrations <-> emit
  sites, api/ annotation vocabulary, chaos YAMLs <-> catalog handlers),
  resolved through the RepoIndex instead of string matching.

Every rule is pure AST: no code under analysis is imported.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from kubeflow_tpu.analysis import config
from kubeflow_tpu.analysis.core import (
    Finding,
    SourceModule,
    dotted_parts,
    resolve_str,
    resolved_callee,
)


class Rule:
    id = ""
    description = ""
    # Incident citations: the shipped bugs (by PR) this rule would have
    # caught — shown by --list-rules so a finding reads as "this class of
    # bug bit us", not "the linter is opinionated".
    incidents: tuple = ()
    # Pointer into ARCHITECTURE.md / CONTRIBUTING.md for the rule's model.
    docs = ""

    def check_module(self, mod: SourceModule, index) -> list:
        return []

    def check_repo(self, index, checked: dict) -> list:
        return []

    def finding(self, mod: SourceModule, node: ast.AST, message: str) -> Finding:
        return Finding(
            self.id, mod.rel, getattr(node, "lineno", 1),
            getattr(node, "col_offset", 0), message,
        )


# -- shared AST helpers ------------------------------------------------------


def _direct_nodes(stmts) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/lambda
    bodies: a nested def only runs when called, and calls are followed
    explicitly by the reachability walker."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _receiver_leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        parts = dotted_parts(call.func.value)
        if parts:
            return parts[-1]
    return None


def _queueish(call: ast.Call) -> bool:
    leaf = _receiver_leaf(call)
    if leaf is None:
        return False
    low = leaf.lower()
    return low == "q" or "queue" in low


def _kwarg_names(call: ast.Call) -> set:
    return {kw.arg for kw in call.keywords if kw.arg}


def _blocking_reason(
    mod: SourceModule, call: ast.Call, in_signal_handler: bool
) -> Optional[str]:
    """Why this call can block indefinitely, or None. Calls that pass an
    explicit bound (join/acquire/wait with a timeout, queue ops with
    block=False or timeout=) are treated as deliberate and allowed."""
    callee = resolved_callee(mod, call)
    if callee == "time.sleep":
        return "time.sleep()"
    if callee and callee.endswith("urlopen"):
        return "network I/O (urlopen)"
    if in_signal_handler and callee == "open":
        return "file I/O (open())"
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    bare = not call.args and not call.keywords
    if attr == "acquire" and bare and in_signal_handler:
        return "unbounded lock acquire()"
    if attr == "join" and bare:
        # Zero-arg join() is Thread.join()/Queue.join() without a bound;
        # str.join always takes an iterable, so no collision.
        if not isinstance(call.func.value, ast.Constant):
            return "unbounded join()"
    if attr == "wait" and bare and in_signal_handler:
        return "unbounded wait()"
    if attr in ("put", "get") and _queueish(call):
        kwargs = _kwarg_names(call)
        if "timeout" in kwargs:
            return None
        for kw in call.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) and not kw.value.value:
                return None
        return f"blocking queue .{attr}()"
    return None


def _function_defs(mod: SourceModule) -> dict:
    defs: dict = {}
    for node in mod.walk():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


# -- family 1: single-module concurrency/safety ------------------------------


class BlockingInSignalHandler(Rule):
    id = "blocking-in-signal-handler"
    description = (
        "Blocking call (queue op, lock acquire, sleep, unbounded join, "
        "file I/O) reachable from a function registered with "
        "signal.signal. The signal may have interrupted the current "
        "owner of the very mutex the call needs (PR 3's emergency-save "
        "deadlock: queue.Queue ops in a SIGTERM handler); do the work on "
        "a dedicated thread and join it with a timeout."
    )
    incidents = (
        "PR 3: emergency-save deadlock — queue.Queue ops in a SIGTERM "
        "handler re-entered the mutex the interrupted thread held",
    )
    docs = "ARCHITECTURE.md#static-analysis — call-graph layer"

    def _handler_nodes(self, graph, mod: SourceModule, reg: ast.Call) -> list:
        """Resolve signal.signal's handler argument to FunctionNodes."""
        target = reg.args[1]
        parts = dotted_parts(target)
        if parts is None:
            return []
        name = parts[-1]
        if len(parts) == 2 and parts[0] == "self":
            enclosing = mod.enclosing_function(reg)
            caller = graph.fn_for(enclosing) if enclosing is not None else None
            if caller is not None and caller.cls:
                for info in graph.classes.get(caller.cls, []):
                    if info.mod is mod:
                        found = graph.class_method(info, name)
                        if found is not None:
                            return [found]
        return list(graph.module_defs.get(mod.name, {}).get(name, []))

    def check_repo(self, index, checked: dict) -> list:
        graph = index.callgraph()
        findings = []
        reported: set = set()
        for rel in sorted(checked):
            mod = checked[rel]
            if mod is None or mod.tree is None:
                continue
            for reg in mod.walk():
                if not isinstance(reg, ast.Call):
                    continue
                if resolved_callee(mod, reg) != "signal.signal":
                    continue
                if len(reg.args) < 2:
                    continue
                if isinstance(reg.args[1], ast.Lambda):
                    for node in _direct_nodes([reg.args[1].body]):
                        if not isinstance(node, ast.Call):
                            continue
                        reason = _blocking_reason(mod, node, True)
                        if reason:
                            findings.append(self._report(
                                mod, node, reason,
                                f"{mod.rel}:{reg.lineno}", ""))
                    continue
                for handler in self._handler_nodes(graph, mod, reg):
                    for fn, _depth, path in graph.reachable(handler):
                        if fn.mod.rel not in checked:
                            continue
                        for node in _direct_nodes(fn.node.body):
                            if not isinstance(node, ast.Call):
                                continue
                            reason = _blocking_reason(fn.mod, node, True)
                            if not reason:
                                continue
                            key = (fn.mod.rel, node.lineno, reg.lineno)
                            if key in reported:
                                continue
                            reported.add(key)
                            via = graph.render_path(path, fn) if path else ""
                            findings.append(self._report(
                                fn.mod, node, reason,
                                f"{mod.rel}:{reg.lineno}", via))
        return findings

    def _report(self, mod, node, reason, reg_at, via) -> Finding:
        via_txt = f" (path: {via})" if via else ""
        return self.finding(
            mod, node,
            f"{reason} reachable from the signal handler registered at "
            f"{reg_at}{via_txt}; run it on a dedicated thread and "
            "join with a timeout instead (PR 3 emergency-save deadlock)",
        )


class LockHeldBlockingCall(Rule):
    id = "lock-held-blocking-call"
    description = (
        "Blocking I/O, time.sleep, or an unbounded join()/queue op "
        "inside a `with <lock>:` block. Every other thread that needs "
        "the lock stalls for the full duration — on the emergency-save "
        "path that turns a slow request into a missed checkpoint window."
    )

    def check_module(self, mod: SourceModule, index) -> list:
        findings = []
        for node in mod.walk():
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lockish = False
            for item in node.items:
                parts = dotted_parts(item.context_expr)
                if parts and "lock" in parts[-1].lower():
                    lockish = True
            if not lockish:
                continue
            for inner in _direct_nodes(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                reason = _blocking_reason(mod, inner, in_signal_handler=False)
                if reason:
                    findings.append(
                        self.finding(
                            mod, inner,
                            f"{reason} while holding the lock taken at "
                            f"line {node.lineno}; compute the value "
                            "outside the critical section or bound the "
                            "wait",
                        )
                    )
        return findings


class SleepInReconcile(Rule):
    id = "sleep-in-reconcile"
    description = (
        "time.sleep inside reconcile-loop code. Reconcilers are "
        "single-threaded and level-triggered: sleeping wedges every "
        "other object's reconcile; return Result(requeue_after=...) and "
        "let the manager's requeue heap own time."
    )

    def _applies(self, mod: SourceModule) -> bool:
        if "/controller/" in f"/{mod.rel}":
            return True
        for node in mod.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name == "reconcile":
                    return True
        return False

    def check_module(self, mod: SourceModule, index) -> list:
        if not self._applies(mod):
            return []
        findings = []
        for node in mod.walk():
            if isinstance(node, ast.Call) and resolved_callee(mod, node) == "time.sleep":
                findings.append(
                    self.finding(
                        mod, node,
                        "time.sleep in reconcile-loop code blocks every "
                        "queued reconcile; return "
                        "Result(requeue_after=...) instead",
                    )
                )
        return findings


class ThreadWithoutDaemon(Rule):
    id = "thread-no-daemon"
    description = (
        "threading.Thread started without a daemon= decision or a "
        "join() story. A forgotten non-daemon thread keeps the process "
        "alive past SIGTERM — the kubelet then SIGKILLs it mid-write."
    )

    def check_module(self, mod: SourceModule, index) -> list:
        findings = []
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            callee = resolved_callee(mod, node)
            if callee != "threading.Thread":
                continue
            if "daemon" in _kwarg_names(node):
                continue
            target = None
            parent = mod.parents.get(node)
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
                t = parent.targets[0]
                target = t.id if isinstance(t, ast.Name) else (
                    t.attr if isinstance(t, ast.Attribute) else None
                )
            if target and self._handled_later(mod, node, target):
                continue
            findings.append(
                self.finding(
                    mod, node,
                    "Thread created without daemon= and never joined in "
                    "this scope; pick one (daemon=True, or a bounded "
                    ".join()) so process exit is deterministic",
                )
            )
        return findings

    def _handled_later(self, mod: SourceModule, call: ast.Call, target: str) -> bool:
        fn = mod.enclosing_function(call)
        scopes = [fn] if fn is not None else []
        if mod.tree is not None:
            scopes.append(mod.tree)  # self.X threads joined from other methods
        for scope in scopes:
            for node in ast.walk(scope):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and t.attr == "daemon"
                            and (p := dotted_parts(t.value))
                            and p[-1] == target
                        ):
                            return True
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"
                    and (p := dotted_parts(node.func.value))
                    and p[-1] == target
                ):
                    return True
        return False


# -- family 2: cross-module contracts ----------------------------------------


def _env_read_name_node(mod: SourceModule, node: ast.AST) -> Optional[ast.AST]:
    """The name-argument node of an env read (`os.environ.get(X)`,
    `os.getenv(X)`, `env.get(X)`, `os.environ[X]`), or None."""
    if isinstance(node, ast.Call):
        callee = resolved_callee(mod, node)
        if callee == "os.getenv":
            return node.args[0] if node.args else None
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in ("get", "pop", "setdefault"):
            if _environish(f.value):
                return node.args[0] if node.args else None
    elif isinstance(node, ast.Subscript):
        if _environish(node.value):
            return node.slice
    return None


def _environish(expr: ast.AST) -> bool:
    parts = dotted_parts(expr)
    if not parts:
        return False
    if parts[-1] == "environ":
        return True
    return len(parts) == 1 and parts[0] == "env"


class EnvReadUnknown(Rule):
    id = "env-read-unknown"
    description = (
        "A TPU_*/JAX_*/MEGASCALE_*/KUBEFLOW_TPU_* env var is read but is "
        "neither produced by the platform (webhook/tpu_env.py "
        "ENV_CONTRACT) nor declared in the analysis allowlist — at "
        "runtime the read silently sees the default value."
    )

    def check_module(self, mod: SourceModule, index) -> list:
        findings = []
        for node in mod.walk():
            name_node = _env_read_name_node(mod, node)
            if name_node is None:
                continue
            name = resolve_str(mod, name_node, index)
            if name is None or not config.ENV_NAME_RE.fullmatch(name):
                continue
            if name in index.env_contract or name in config.ENV_READ_ALLOWLIST:
                continue
            findings.append(
                self.finding(
                    mod, node,
                    f"env var {name!r} is read but no producer declares "
                    "it: add it to ENV_CONTRACT in "
                    "kubeflow_tpu/webhook/tpu_env.py (with the producer) "
                    "or to ENV_READ_ALLOWLIST in "
                    "kubeflow_tpu/analysis/config.py (with a reason)",
                )
            )
        return findings


class EnvLiteralOutsideContract(Rule):
    id = "env-literal"
    description = (
        "A platform env var name is spelled as a string literal outside "
        "its contract home. The webhook<->runtime env contract drifted "
        "exactly this way before: import the name from "
        "kubeflow_tpu/webhook/tpu_env.py or kubeflow_tpu/api/annotations.py."
    )

    def check_module(self, mod: SourceModule, index) -> list:
        if mod.rel in config.ENV_NAME_HOMES:
            return []
        findings = []
        for node in mod.walk():
            if not (isinstance(node, ast.Constant) and isinstance(node.value, str)):
                continue
            value = node.value
            if not config.ENV_NAME_RE.fullmatch(value):
                continue
            if value in config.ENV_READ_ALLOWLIST:
                continue
            findings.append(
                self.finding(
                    mod, node,
                    f"env var name {value!r} re-typed as a literal; "
                    "import it from kubeflow_tpu/webhook/tpu_env.py "
                    "(ENV_CONTRACT) or kubeflow_tpu/api/annotations.py",
                )
            )
        return findings


class MetricLiteralUnregistered(Rule):
    id = "metric-unregistered"
    description = (
        "A metric family name is referenced that metrics/metrics.py "
        "never registers — the scrape/assertion reads a series that "
        "will never exist."
    )

    def check_module(self, mod: SourceModule, index) -> list:
        if mod.rel == config.METRICS_MODULE:
            return []
        findings = []
        for node in mod.walk():
            if not (isinstance(node, ast.Constant) and isinstance(node.value, str)):
                continue
            value = node.value
            if not config.METRIC_NAME_RE.fullmatch(value):
                continue
            if self._registered(value, index):
                continue
            findings.append(
                self.finding(
                    mod, node,
                    f"metric name {value!r} is not registered in "
                    "kubeflow_tpu/metrics/metrics.py (after stripping "
                    "prometheus series suffixes); register it or fix the "
                    "name drift",
                )
            )
        return findings

    @staticmethod
    def _registered(name: str, index) -> bool:
        if name in index.metric_names:
            return True
        for suffix in config.METRIC_SERIES_SUFFIXES:
            if name.endswith(suffix) and name[: -len(suffix)] in index.metric_names:
                return True
        return False


class MetricAttrUnregistered(Rule):
    id = "metric-attr-unregistered"
    description = (
        "An attribute is read off a Metrics object that Metrics.__init__ "
        "never defines — the emit site would AttributeError the first "
        "time that code path runs in production."
    )

    def check_module(self, mod: SourceModule, index) -> list:
        if mod.rel == config.METRICS_MODULE:
            return []
        findings = []
        for node in mod.walk():
            if isinstance(node, ast.Attribute):
                parts = dotted_parts(node)
                if parts and parts[0] == "kubeflow_tpu":
                    continue  # dotted module path, not a Metrics object
                v = node.value
                base_is_metrics = (
                    isinstance(v, ast.Name) and v.id == "metrics"
                ) or (isinstance(v, ast.Attribute) and v.attr == "metrics")
                if not base_is_metrics:
                    continue
                attr = node.attr
                if attr[:1].isupper() or attr == "metrics":
                    continue  # module alias (metrics.Metrics / metrics.server)
                if attr in index.metric_attrs or attr in config.METRICS_OBJECT_API:
                    continue
                findings.append(self._unknown(mod, node, attr))
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "getattr"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                parts = dotted_parts(node.args[0])
                if not parts or parts[-1] != "metrics":
                    continue
                attr = node.args[1].value
                if attr in index.metric_attrs or attr in config.METRICS_OBJECT_API:
                    continue
                findings.append(self._unknown(mod, node, attr))
        return findings

    def _unknown(self, mod: SourceModule, node: ast.AST, attr: str) -> Finding:
        return self.finding(
            mod, node,
            f"Metrics object has no attribute {attr!r}; register the "
            "metric in kubeflow_tpu/metrics/metrics.py or fix the emit "
            "site",
        )


class MetricNameScheme(Rule):
    id = "metric-name-scheme"
    description = (
        "Registered metric families must follow the tpu_* naming scheme "
        "(reference notebook_* names are grandfathered) so dashboards "
        "can select the platform's series with one matcher."
    )

    def check_module(self, mod: SourceModule, index) -> list:
        findings = []
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            callee = resolved_callee(mod, node) or ""
            if callee.startswith("collections."):
                continue
            leaf = callee.rsplit(".", 1)[-1]
            if leaf not in config.PROM_CONSTRUCTORS:
                continue
            if not (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            if len(node.args) < 2 and not (
                _kwarg_names(node) & {"documentation", "registry", "labelnames"}
            ):
                continue  # not a prometheus registration signature
            name = node.args[0].value
            if config.TPU_METRIC_RE.fullmatch(name):
                continue
            if name in config.REFERENCE_METRIC_NAMES:
                continue
            findings.append(
                self.finding(
                    mod, node,
                    f"metric family {name!r} does not follow the tpu_* "
                    "naming scheme (and is not a grandfathered reference "
                    "name)",
                )
            )
        return findings


class SpanUnended(Rule):
    id = "span-unended"
    description = (
        "A start_span() call whose span cannot be shown to end on every "
        "path: use it as a context manager (`with ...start_span(...)`), "
        "or assign it to a name a `finally` block .end()s. An exception "
        "between start and a bare .end() leaks the span AND leaves it "
        "installed as the thread's current span, so every later span on "
        "that thread parents under a request that already finished. "
        "begin_span (the cross-thread handoff form) is exempt — its "
        "spans end in another thread's callback by design."
    )

    def check_module(self, mod: SourceModule, index) -> list:
        findings = []
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            if not (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "start_span"
            ):
                continue
            parent = mod.parents.get(node)
            if isinstance(parent, ast.withitem):
                continue
            if self._ended_in_finally(mod, node, parent):
                continue
            findings.append(
                self.finding(
                    mod, node,
                    "span from start_span() is neither a `with` context "
                    "manager nor .end()ed in a finally block; an "
                    "exception on this path leaks an unended span that "
                    "stays installed as the thread's current span (use "
                    "`with`, try/finally + .end(), or begin_span for a "
                    "span another thread ends)",
                )
            )
        return findings

    @staticmethod
    def _ended_in_finally(
        mod: SourceModule, call: ast.Call, parent
    ) -> bool:
        if not (
            isinstance(parent, ast.Assign)
            and len(parent.targets) == 1
            and isinstance(parent.targets[0], ast.Name)
        ):
            return False
        target = parent.targets[0].id
        scope = mod.enclosing_function(call) or mod.tree
        if scope is None:
            return False
        for node in ast.walk(scope):
            if not isinstance(node, ast.Try) or not node.finalbody:
                continue
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "end"
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == target
                    ):
                        return True
        return False


class MetricStatsParity(Rule):
    id = "metric-stats-parity"
    description = (
        "Every tpu_serving_*/tpu_engine_* metric family registered in "
        "metrics/metrics.py must be surfaced in a servers' JSON /stats "
        "payload, recorded in the STATS_PARITY table (family -> /stats "
        "key). An operator tailing /stats and a dashboard scraping "
        "/metrics must never disagree about which observables exist."
    )

    @staticmethod
    def _parity_entries(mod: SourceModule) -> tuple:
        """(dict_node, {family: (stats_key_or_None, lineno)}) for a
        module-level STATS_PARITY dict literal, or (None, {})."""
        for node in mod.walk():
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Dict
            ):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "STATS_PARITY"
                for t in node.targets
            ):
                continue
            entries: dict = {}
            for key, value in zip(node.value.keys, node.value.values):
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    stats_key = (
                        value.value
                        if isinstance(value, ast.Constant)
                        and isinstance(value.value, str)
                        else None
                    )
                    entries[key.value] = (stats_key, key.lineno)
            return node, entries
        return None, {}

    @staticmethod
    def _local_registrations(mod: SourceModule) -> list:
        """(family, call_node) for every prometheus registration in
        THIS module (module-local, so fixtures are self-contained)."""
        out = []
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            callee = resolved_callee(mod, node) or ""
            if callee.rsplit(".", 1)[-1] not in config.PROM_CONSTRUCTORS:
                continue
            if (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.append((node.args[0].value, node))
        return out

    def check_module(self, mod: SourceModule, index) -> list:
        dict_node, parity = self._parity_entries(mod)
        serving = [
            (name, node)
            for name, node in self._local_registrations(mod)
            if config.STATS_PARITY_FAMILY_RE.fullmatch(name)
        ]
        if dict_node is None and not serving:
            return []
        findings = []
        for name, node in serving:
            if name not in parity:
                findings.append(
                    self.finding(
                        mod, node,
                        f"serving/engine metric family {name!r} is "
                        "registered but has no STATS_PARITY entry "
                        "mapping it to a /stats key — the JSON /stats "
                        "view and the Prometheus view just diverged",
                    )
                )
        registered = {n for n, _ in self._local_registrations(mod)}
        for name, (stats_key, line) in parity.items():
            if name not in registered:
                findings.append(
                    Finding(
                        self.id, mod.rel, line, 0,
                        f"STATS_PARITY lists {name!r} but this module "
                        "never registers that family",
                    )
                )
            if stats_key is None:
                findings.append(
                    Finding(
                        self.id, mod.rel, line, 0,
                        f"STATS_PARITY entry for {name!r} must map to "
                        "a /stats key string literal",
                    )
                )
        return findings

    def check_repo(self, index, checked: dict) -> list:
        if config.METRICS_MODULE not in checked:
            return []
        mod = index.by_rel.get(config.METRICS_MODULE)
        if mod is None:
            return []
        dict_node, parity = self._parity_entries(mod)
        if dict_node is None:
            return [
                Finding(
                    self.id, config.METRICS_MODULE, 1, 0,
                    "metrics module defines no STATS_PARITY table; the "
                    "serving families' /stats surfacing is unrecorded",
                )
            ]
        surface_literals: set = set()
        for rel in config.STATS_SURFACE_MODULES:
            smod = index.by_rel.get(rel)
            if smod is None:
                continue
            for node in smod.walk():
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    surface_literals.add(node.value)
        findings = []
        for name, (stats_key, line) in parity.items():
            if stats_key is not None and stats_key not in surface_literals:
                findings.append(
                    Finding(
                        self.id, config.METRICS_MODULE, line, 0,
                        f"STATS_PARITY maps {name!r} to /stats key "
                        f"{stats_key!r}, but that key never appears in "
                        + " or ".join(config.STATS_SURFACE_MODULES),
                    )
                )
        return findings


class AnnotationLiteral(Rule):
    id = "annotation-literal"
    description = (
        "A notebooks.kubeflow.org/* style annotation/label/finalizer key "
        "is spelled as a literal outside kubeflow_tpu/api/. The api/ "
        "modules are the wire-contract vocabulary; a re-typed key drifts "
        "silently when the contract changes."
    )

    def check_module(self, mod: SourceModule, index) -> list:
        if mod.rel.startswith(config.ANNOTATION_HOME_PREFIX):
            return []
        findings = []
        for node in mod.walk():
            if not (isinstance(node, ast.Constant) and isinstance(node.value, str)):
                continue
            if not config.ANNOTATION_RE.fullmatch(node.value):
                continue
            findings.append(
                self.finding(
                    mod, node,
                    f"annotation/label key {node.value!r} spelled inline; "
                    "define it in kubeflow_tpu/api/annotations.py and "
                    "import it",
                )
            )
        return findings


class ChaosParity(Rule):
    id = "chaos-parity"
    description = (
        "chaos/experiments/*.yaml and the chaos_catalog handler registry "
        "must cover each other exactly: a YAML without a handler never "
        "runs; a handler without a YAML certifies a hypothesis nobody "
        "declared."
    )

    def check_repo(self, index, checked: dict) -> list:
        if config.CHAOS_CATALOG_MODULE not in checked:
            return []
        catalog_rel = config.CHAOS_CATALOG_MODULE
        findings = []

        def f(line: int, message: str, path: str = catalog_rel) -> Finding:
            return Finding(self.id, path, line, 0, message)

        if index.chaos_yaml_error:
            findings.append(f(1, f"chaos YAML problem: {index.chaos_yaml_error}"))
        yamls = {t for t in index.chaos_yaml_types if not t.startswith("<")}
        handlers = index.chaos_handler_types
        declared = index.chaos_injection_types
        kinds = index.chaos_target_kinds
        for t in sorted(yamls - handlers):
            findings.append(
                f(
                    1,
                    f"experiment {index.chaos_yaml_types[t]} declares "
                    f"injection {t!r} but ChaosRunner registers no "
                    "handler for it",
                    path=index.chaos_yaml_types[t],
                )
            )
        for t in sorted(handlers - yamls):
            findings.append(
                f(
                    index.chaos_handler_line or 1,
                    f"handler {t!r} has no declarative experiment under "
                    "chaos/experiments/",
                )
            )
        for t in sorted(declared - handlers):
            findings.append(
                f(
                    index.chaos_injection_line or 1,
                    f"INJECTION_TYPES declares {t!r} with no registered "
                    "handler",
                )
            )
        for t in sorted(handlers - declared):
            findings.append(
                f(
                    index.chaos_handler_line or 1,
                    f"handler {t!r} missing from INJECTION_TYPES (schema "
                    "validation would reject its experiments)",
                )
            )
        for t in sorted(declared - kinds):
            findings.append(
                f(
                    index.chaos_target_line or 1,
                    f"injection {t!r} missing from "
                    "TARGET_KIND_FOR_INJECTION",
                )
            )
        for t in sorted(kinds - declared):
            findings.append(
                f(
                    index.chaos_target_line or 1,
                    f"TARGET_KIND_FOR_INJECTION lists unknown injection "
                    f"{t!r}",
                )
            )
        return findings


class UndeadlinedClaim(Rule):
    id = "undeadlined-claim"
    description = (
        "Warm-slice claim (claim_warm_slice) without deadline=, or a "
        "cross-slice HTTP connection (http.client.HTTP[S]Connection) "
        "without timeout=. Both sit on migration/recovery paths where an "
        "unbounded wait wedges the very pipeline that exists to beat a "
        "deadline: the fenced claim walk can loop while concurrent "
        "claimants steal every candidate, and a flip/restore probe can "
        "hang on a half-dead slice. Migration degrades to the reactive "
        "ladder on a blown budget — but only if every wait is bounded."
    )

    _HTTP_CONSTRUCTORS = ("HTTPConnection", "HTTPSConnection")

    def check_module(self, mod: SourceModule, index) -> list:
        findings = []
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            callee = resolved_callee(mod, node)
            if callee is None:
                continue
            leaf = callee.split(".")[-1]
            if leaf == "claim_warm_slice":
                # The definition site itself is not a call; every actual
                # call must carry an explicit bound on the candidate walk.
                if "deadline" not in _kwarg_names(node):
                    findings.append(
                        self.finding(
                            mod, node,
                            "claim_warm_slice without deadline=: the "
                            "fenced candidate walk is unbounded under "
                            "claim contention; pass deadline="
                            "time.perf_counter() + budget so the caller "
                            "falls back instead of wedging",
                        )
                    )
            elif leaf in self._HTTP_CONSTRUCTORS:
                if "timeout" not in _kwarg_names(node):
                    findings.append(
                        self.finding(
                            mod, node,
                            f"{leaf} without timeout=: a cross-slice "
                            "HTTP call on a recovery/migration path can "
                            "hang on a half-dead host; every connection "
                            "needs an explicit timeout",
                        )
                    )
        return findings


class UnboundedFanout(Rule):
    id = "kftpu-unbounded-fanout"
    description = (
        "Loop issuing HTTP requests over ring members (peers / "
        "successors / ring_nodes) without a fanout bound or without a "
        "per-hop timeout. The peer-fetch and reroute ladders multiply "
        "every per-hop cost by the peer count: an unsliced walk over "
        "the whole ring turns one slow replica into a fleet-wide stall, "
        "and a timeout-less hop inside the loop hangs the entire walk "
        "on the first half-dead host. Bound the peer set at the loop "
        "header (slice, islice, or an explicit successors() budget) or "
        "break on a fanout counter, and give every in-loop connection "
        "an explicit timeout."
    )

    _HTTP_CONSTRUCTORS = ("HTTPConnection", "HTTPSConnection")
    _RINGISH = ("peers", "successors", "ring_nodes")
    _UNWRAP = ("enumerate", "sorted", "list", "reversed", "tuple")

    def _unwrap(self, expr):
        # enumerate(peers) / sorted(peers) etc. — the bound (or its
        # absence) belongs to the inner iterable.
        while (isinstance(expr, ast.Call) and expr.args
               and isinstance(expr.func, ast.Name)
               and expr.func.id in self._UNWRAP):
            expr = expr.args[0]
        return expr

    def _leaf_name(self, expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            return expr.attr
        return None

    def _classify_iter(self, expr) -> Optional[bool]:
        """None if not ring-ish, else whether the walk is bounded at
        the loop header."""
        expr = self._unwrap(expr)
        if isinstance(expr, ast.Subscript) and isinstance(
                expr.slice, ast.Slice):
            # peers[:fanout] — bounded regardless of the inner name.
            return True if self._classify_iter(expr.value) is not None \
                else None
        if isinstance(expr, ast.Call):
            leaf = None
            if isinstance(expr.func, ast.Attribute):
                leaf = expr.func.attr
            elif isinstance(expr.func, ast.Name):
                leaf = expr.func.id
            if leaf == "islice":
                return True
            if leaf == "successors":
                # successors(key, limit) carries an explicit budget —
                # unless the limit is len(<ring>), i.e. the whole ring.
                limit = expr.args[1] if len(expr.args) > 1 else None
                if (isinstance(limit, ast.Call)
                        and isinstance(limit.func, ast.Name)
                        and limit.func.id == "len"):
                    return False
                return limit is not None
            return None
        name = self._leaf_name(expr)
        if name is not None and any(
                r in name.lower() for r in self._RINGISH):
            return False
        return None

    def check_module(self, mod: SourceModule, index) -> list:
        findings = []
        for node in mod.walk():
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            bounded = self._classify_iter(node.iter)
            if bounded is None:
                continue
            http_calls = []
            has_break = False
            for sub in _direct_nodes(node.body):
                if isinstance(sub, ast.Break):
                    has_break = True
                if not isinstance(sub, ast.Call):
                    continue
                callee = resolved_callee(mod, sub)
                if callee is None:
                    continue
                leaf = callee.split(".")[-1]
                if leaf in self._HTTP_CONSTRUCTORS or callee.endswith(
                        "urlopen"):
                    http_calls.append((sub, leaf))
            if not http_calls:
                continue
            if not bounded and not has_break:
                findings.append(
                    self.finding(
                        mod, node,
                        "HTTP fan-out over an unbounded ring walk: "
                        "slice the peer set (peers[:fanout]), pass an "
                        "explicit successors() budget, or break on a "
                        "fanout counter so one walk cannot visit the "
                        "whole fleet",
                    )
                )
            for call, leaf in http_calls:
                if leaf in self._HTTP_CONSTRUCTORS and \
                        "timeout" not in _kwarg_names(call):
                    findings.append(
                        self.finding(
                            mod, call,
                            f"{leaf} inside a ring fan-out loop without "
                            "timeout=: the walk's whole budget hangs on "
                            "the first half-dead peer; every hop needs "
                            "its own deadline",
                        )
                    )
        return findings


class SuppressionHygiene(Rule):
    id = "suppression-hygiene"
    description = (
        "Every `# kftpu-lint: disable=` needs a real rule id and a "
        "justification after the dash — an unexplained suppression is "
        "how dead rules accumulate. This rule cannot be suppressed."
    )

    def check_module(self, mod: SourceModule, index) -> list:
        findings = []
        known = rule_ids()
        for line in getattr(mod, "malformed_suppression_lines", []):
            findings.append(
                Finding(
                    self.id, mod.rel, line, 0,
                    "kftpu-lint marker present but not parseable; "
                    "expected `# kftpu-lint: disable=<rule>[,<rule>] — "
                    "justification`",
                )
            )
        for sup in mod.suppressions:
            for rule in sup.rules:
                if rule not in known:
                    findings.append(
                        Finding(
                            self.id, mod.rel, sup.line, 0,
                            f"suppression names unknown rule {rule!r}",
                        )
                    )
            if not sup.justification:
                findings.append(
                    Finding(
                        self.id, mod.rel, sup.line, 0,
                        "suppression has no justification; say WHY after "
                        "an em dash (— reason)",
                    )
                )
        return findings


# Interprocedural rule families live in their own modules (they ride the
# shared call graph + lock model); imported here so ALL_RULES stays the
# single registry the engine and rule_ids() consume. Imported late to
# avoid a cycle (concurrency/jaxrules use the Rule helpers above).
from kubeflow_tpu.analysis.concurrency import CONCURRENCY_RULES  # noqa: E402
from kubeflow_tpu.analysis.jaxrules import JAX_RULES  # noqa: E402

ALL_RULES = [
    BlockingInSignalHandler(),
    LockHeldBlockingCall(),
    SleepInReconcile(),
    ThreadWithoutDaemon(),
    EnvReadUnknown(),
    EnvLiteralOutsideContract(),
    MetricLiteralUnregistered(),
    MetricAttrUnregistered(),
    MetricNameScheme(),
    MetricStatsParity(),
    SpanUnended(),
    AnnotationLiteral(),
    ChaosParity(),
    UndeadlinedClaim(),
    UnboundedFanout(),
    SuppressionHygiene(),
    *CONCURRENCY_RULES,
    *JAX_RULES,
]

# `parse-error` is emitted by the engine itself for unparseable files.
_ENGINE_RULES = ("parse-error",)


def rule_ids() -> set:
    return {r.id for r in ALL_RULES} | set(_ENGINE_RULES)
