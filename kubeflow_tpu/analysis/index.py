"""kftpu-lint cross-module index.

The piece pattern-level tools cannot build: one pass over the repo
collects every contract surface — the env-var contract table, registered
metric families, the api/ constants vocabulary, chaos-catalog handler
registrations and the declarative experiment YAMLs — so rules can answer
"is this name part of the contract?" instead of "does this line match?".
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Optional

from kubeflow_tpu.analysis import config
from kubeflow_tpu.analysis.core import SourceModule, dotted_parts, resolved_callee


def _assign_targets(node: ast.AST):
    """Normalize Assign/AnnAssign to (targets, value); ([], None) otherwise."""
    if isinstance(node, ast.Assign):
        return node.targets, node.value
    if isinstance(node, ast.AnnAssign) and node.value is not None:
        return [node.target], node.value
    return [], None


class RepoIndex:
    # (frozenset of module ids, CallGraph) — last graph built anywhere in
    # the process; see callgraph() for why sharing is sound.
    _graph_cache: tuple = (None, None)

    def __init__(self, repo_root: Path):
        self.repo_root = repo_root
        self.modules: dict = {}  # dotted name -> SourceModule
        self.by_rel: dict = {}  # rel path -> SourceModule
        # env contract: var name -> producer description
        self.env_contract: dict = {}
        self.env_contract_line = 0
        # metrics: Metrics attribute -> family name, plus the name set
        self.metric_attrs: dict = {}
        self.metric_names: set = set()
        # chaos catalog: injection types from the three registration sites
        self.chaos_injection_types: set = set()
        self.chaos_injection_line = 0
        self.chaos_handler_types: set = set()
        self.chaos_handler_line = 0
        self.chaos_target_kinds: set = set()
        self.chaos_target_line = 0
        # chaos YAMLs: injection type -> rel path of the experiment file
        self.chaos_yaml_types: dict = {}
        self.chaos_yaml_error: Optional[str] = None
        # interprocedural layer: built lazily so index-only tests (and
        # the contract rules) never pay for it.
        self._callgraph = None

    def callgraph(self):
        """The shared repo-wide call graph (callgraph.CallGraph).

        Graphs are pure functions of the module set, and engine-level
        module caching means successive run_analysis() calls in one
        process usually index the *same* SourceModule objects — so an
        identical module set reuses the previous index's graph instead
        of re-resolving every edge.
        """
        if self._callgraph is None:
            from kubeflow_tpu.analysis.callgraph import CallGraph

            key = frozenset(id(m) for m in self.by_rel.values())
            cached_key, cached = RepoIndex._graph_cache
            if key == cached_key and cached is not None:
                self._callgraph = cached
            else:
                self._callgraph = CallGraph(self)
                RepoIndex._graph_cache = (key, self._callgraph)
        return self._callgraph

    def add(self, mod: SourceModule) -> None:
        self.modules[mod.name] = mod
        self.by_rel[mod.rel] = mod

    def get_constant(self, owner: str, attr: str) -> Optional[str]:
        mod = self.modules.get(owner)
        if mod is None:
            return None
        return mod.constants.get(attr)

    # -- builders ------------------------------------------------------------

    def build(self) -> None:
        env_mod = self.by_rel.get(config.ENV_CONTRACT_MODULE)
        if env_mod is not None:
            self._index_env_contract(env_mod)
        metrics_mod = self.by_rel.get(config.METRICS_MODULE)
        if metrics_mod is not None:
            self._index_metrics(metrics_mod)
        chaos_mod = self.by_rel.get(config.CHAOS_CATALOG_MODULE)
        if chaos_mod is not None:
            self._index_chaos_catalog(chaos_mod)
        self._index_chaos_yamls()

    def _index_env_contract(self, mod: SourceModule) -> None:
        for node in mod.walk():
            targets, dict_value = _assign_targets(node)
            if not any(
                isinstance(t, ast.Name) and t.id == "ENV_CONTRACT" for t in targets
            ):
                continue
            if not isinstance(dict_value, ast.Dict):
                continue
            self.env_contract_line = node.lineno
            from kubeflow_tpu.analysis.core import resolve_str

            for key, value in zip(dict_value.keys, dict_value.values):
                name = resolve_str(mod, key, self) if key is not None else None
                if name is None:
                    continue
                desc = value.value if isinstance(value, ast.Constant) else ""
                self.env_contract[name] = desc if isinstance(desc, str) else ""

    def _index_metrics(self, mod: SourceModule) -> None:
        for node in mod.walk():
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            callee = resolved_callee(mod, node.value) or ""
            leaf = callee.rsplit(".", 1)[-1]
            if leaf not in config.PROM_CONSTRUCTORS:
                continue
            if not (
                node.value.args
                and isinstance(node.value.args[0], ast.Constant)
                and isinstance(node.value.args[0].value, str)
            ):
                continue
            family = node.value.args[0].value
            self.metric_names.add(family)
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    self.metric_attrs[t.attr] = family
                elif isinstance(t, ast.Name):
                    self.metric_attrs[t.id] = family

    def _index_chaos_catalog(self, mod: SourceModule) -> None:
        for node in mod.walk():
            targets, value = _assign_targets(node)
            for t in targets:
                tname = t.id if isinstance(t, ast.Name) else (
                    t.attr if isinstance(t, ast.Attribute) else None
                )
                if tname == "INJECTION_TYPES" and isinstance(
                    value, (ast.Tuple, ast.List)
                ):
                    self.chaos_injection_line = node.lineno
                    for elt in value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            self.chaos_injection_types.add(elt.value)
                elif tname == "TARGET_KIND_FOR_INJECTION" and isinstance(
                    value, ast.Dict
                ):
                    self.chaos_target_line = node.lineno
                    for key in value.keys:
                        if isinstance(key, ast.Constant) and isinstance(
                            key.value, str
                        ):
                            self.chaos_target_kinds.add(key.value)
                elif tname == "_handlers" and isinstance(value, ast.Dict):
                    self.chaos_handler_line = node.lineno
                    for key in value.keys:
                        if isinstance(key, ast.Constant) and isinstance(
                            key.value, str
                        ):
                            self.chaos_handler_types.add(key.value)

    def _index_chaos_yamls(self) -> None:
        exp_dir = self.repo_root / config.CHAOS_EXPERIMENTS_DIR
        if not exp_dir.is_dir():
            return
        try:
            import yaml
        except ImportError:  # pragma: no cover - yaml ships with the repo
            self.chaos_yaml_error = "pyyaml unavailable; chaos parity skipped"
            return
        for path in sorted(exp_dir.glob("*.yaml")):
            rel = path.relative_to(self.repo_root).as_posix()
            try:
                docs = list(yaml.safe_load_all(path.read_text()))
            except Exception as err:  # malformed YAML is a parity finding
                self.chaos_yaml_types[f"<unparseable:{rel}>"] = rel
                self.chaos_yaml_error = f"{rel}: {err}"
                continue
            for doc in docs:
                if not isinstance(doc, dict):
                    continue
                itype = (
                    doc.get("spec", {}).get("injection", {}).get("type")
                )
                if isinstance(itype, str):
                    self.chaos_yaml_types.setdefault(itype, rel)
