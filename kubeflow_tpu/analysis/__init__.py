"""kftpu-lint: the in-repo AST analysis engine.

The reference repo's only correctness tooling is a pattern-level semgrep
ruleset; patterns cannot see across files, and the bug classes this repo
actually shipped (PR 3's blocking-queue-op-inside-a-signal-handler
deadlock, env-contract literals drifting between webhook and runtime) are
exactly the cross-file ones. This package loads the repo into per-module
ASTs plus a cross-module index (ENV_CONTRACT, registered metrics,
annotation constants, chaos-catalog handlers) and evaluates two rule
families: single-module concurrency/safety rules and cross-module
contract rules. See ARCHITECTURE.md §static-analysis.

Run it:  python -m kubeflow_tpu.analysis [paths ...] [--format json]
Gate:    tests/test_analysis.py asserts zero unsuppressed findings on
         kubeflow_tpu/ (tier-1).
"""

from kubeflow_tpu.analysis.core import Finding  # noqa: F401
from kubeflow_tpu.analysis.engine import Report, run_analysis  # noqa: F401
from kubeflow_tpu.analysis.rules import ALL_RULES, rule_ids  # noqa: F401
