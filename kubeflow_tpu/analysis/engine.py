"""kftpu-lint engine: load -> index -> check -> suppress -> gate -> report.

The whole kubeflow_tpu package is always loaded into the index (contract
tables live in webhook/, metrics/, api/, k8s/ and rules must resolve
references into them no matter which subset of files is being checked);
the target paths only decide which modules get *checked*.

Gating (v2): after suppressions, the checked-in findings baseline
(analysis/baseline.json) and the optional --diff changed-line filter
mark findings `baselined` / `out_of_diff`; the exit code rides on what
remains (Report.gating). With the repo's standing empty baseline and no
diff range, gating == unsuppressed — PR 4 behavior unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

from kubeflow_tpu.analysis import baseline as baseline_mod
from kubeflow_tpu.analysis import config
from kubeflow_tpu.analysis.core import Finding, load_module
from kubeflow_tpu.analysis.index import RepoIndex
from kubeflow_tpu.analysis.rules import ALL_RULES

# Rules whose findings may never be suppressed: a suppressed suppression
# problem (or parse error) would be invisible by construction.
UNSUPPRESSABLE = {"suppression-hygiene", "parse-error"}

PACKAGE_DIR = Path(__file__).resolve().parents[1]  # .../kubeflow_tpu
REPO_ROOT = PACKAGE_DIR.parent


@dataclass
class Report:
    findings: list = field(default_factory=list)
    checked: list = field(default_factory=list)  # rel paths actually checked

    @property
    def unsuppressed(self) -> list:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list:
        return [f for f in self.findings if f.suppressed]

    @property
    def baselined(self) -> list:
        return [f for f in self.unsuppressed if getattr(f, "baselined", False)]

    @property
    def out_of_diff(self) -> list:
        return [f for f in self.unsuppressed if getattr(f, "out_of_diff", False)]

    @property
    def gating(self) -> list:
        """What actually fails the build: unsuppressed findings that are
        neither baselined nor outside the requested diff range."""
        return [
            f
            for f in self.unsuppressed
            if not getattr(f, "baselined", False)
            and not getattr(f, "out_of_diff", False)
        ]

    @property
    def exit_code(self) -> int:
        return 1 if self.gating else 0

    def as_dict(self) -> dict:
        return {
            "checked_files": len(self.checked),
            "findings": [f.as_dict() for f in self.findings],
            "unsuppressed": len(self.unsuppressed),
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "out_of_diff": len(self.out_of_diff),
            "gating": len(self.gating),
        }

    def render_text(self, include_suppressed: bool = False) -> str:
        shown = self.findings if include_suppressed else self.gating
        lines = [f.render() for f in shown]
        lines.append(
            f"kftpu-lint: {len(self.checked)} files checked, "
            f"{len(self.gating)} gating findings "
            f"({len(self.suppressed)} suppressed, "
            f"{len(self.baselined)} baselined, "
            f"{len(self.out_of_diff)} outside diff)"
        )
        return "\n".join(lines)


def _rel_and_name(path: Path, repo_root: Path) -> tuple:
    try:
        rel = path.relative_to(repo_root).as_posix()
    except ValueError:
        return path.name, path.stem
    return rel, rel[:-3].replace("/", ".") if rel.endswith(".py") else rel


def _iter_py_files(target: Path) -> Iterable[Path]:
    if target.is_file():
        if target.suffix == ".py":
            yield target
        return
    for path in sorted(target.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        yield path


# Parsed-module cache for the always-loaded package tree. The test suite
# calls run_analysis() ~20 times per process (repo gate, revert tests,
# baseline/diff/SARIF workflows) and re-parsing 96 modules each time
# dominated its runtime. SourceModules are read-only after load, and the
# (mtime_ns, size) key invalidates entries when a test rewrites a file.
# Target paths outside kubeflow_tpu/ (fixtures, tmp copies) are always
# loaded fresh.
_MODULE_CACHE: dict = {}


def _load_package_module(path: Path, rel: str, name: str):
    try:
        stat = path.stat()
        key = (str(path), stat.st_mtime_ns, stat.st_size)
    except OSError:
        return load_module(path, rel, name)
    cached = _MODULE_CACHE.get(key)
    if cached is None or cached.rel != rel:
        cached = _MODULE_CACHE[key] = load_module(path, rel, name)
    return cached


def run_analysis(
    paths: Optional[Iterable] = None,
    repo_root: Optional[Path] = None,
    baseline_path: Optional[Path] = None,
    diff_range: Optional[str] = None,
) -> Report:
    root = Path(repo_root).resolve() if repo_root else REPO_ROOT
    targets = [Path(p).resolve() for p in (paths or [])] or [root / "kubeflow_tpu"]

    index = RepoIndex(root)
    package_dir = root / "kubeflow_tpu"
    if package_dir.is_dir():
        for path in _iter_py_files(package_dir):
            rel, name = _rel_and_name(path, root)
            index.add(_load_package_module(path, rel, name))

    checked: dict = {}  # rel -> SourceModule
    for target in targets:
        for path in _iter_py_files(target):
            rel, name = _rel_and_name(path, root)
            mod = index.by_rel.get(rel)
            if mod is None:
                mod = load_module(path, rel, name)
                index.add(mod)
            if rel.startswith(config.SELF_PREFIX):
                continue  # the linter's own tables encode the checked names
            checked[rel] = mod

    index.build()

    findings: list = []
    for rel in sorted(checked):
        mod = checked[rel]
        if mod.parse_error is not None:
            findings.append(
                Finding("parse-error", rel, 1, 0, f"cannot parse: {mod.parse_error}")
            )
            continue
        for rule in ALL_RULES:
            findings.extend(rule.check_module(mod, index))
    for rule in ALL_RULES:
        findings.extend(rule.check_repo(index, checked))

    for finding in findings:
        if finding.rule in UNSUPPRESSABLE:
            continue
        mod = index.by_rel.get(finding.path)
        if mod is None:
            continue
        sup = mod.suppression_for(finding.rule, finding.line)
        if sup is not None and sup.justification:
            finding.suppressed = True
            finding.justification = sup.justification

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    report = Report(findings=findings, checked=sorted(checked))

    # baseline_path=False disables the baseline entirely (--no-baseline)
    entries = (
        [] if baseline_path is False
        else baseline_mod.load_baseline(baseline_path)
    )
    if entries:
        baseline_mod.apply_baseline(report, entries, index)
    if diff_range:
        changed = baseline_mod.changed_lines(diff_range, root)
        if changed is None:
            raise SystemExit(
                f"kftpu-lint: git diff failed for range {diff_range!r}"
            )
        baseline_mod.apply_diff_filter(report, changed)
    report.index = index  # for baseline regeneration / fingerprinting
    return report
