"""Deploy manifests: CRD, RBAC, managers, webhooks, overlays, samples."""

from kubeflow_tpu.deploy.manifests import notebook_crd  # noqa: F401
from kubeflow_tpu.deploy.render import render_all  # noqa: F401
