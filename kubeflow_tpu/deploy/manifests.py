"""Kubernetes deploy manifests, built as Python dicts and rendered to YAML.

Reference parity: the reference ships static kustomize trees —
components/notebook-controller/config/{crd/bases,default,manager,rbac,
overlays/{standalone,kubeflow,openshift},samples} and
components/odh-notebook-controller/config/{base,crd/external,default,
manager,rbac,webhook,samples}. Instead of hand-maintained YAML, this module
is the single source of truth; ``ci/generate_manifests.py`` renders it into
``config/`` and the drift test (tests/test_manifests.py) plays the role of
the reference's generator-drift CI check (ci/generate_code.sh).
"""

from __future__ import annotations

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import GROUP, KIND, MAX_NAME_LENGTH, VERSIONS
from kubeflow_tpu.tpu.topology import ACCELERATORS, _ALIASES

PLURAL = "notebooks"
CRD_NAME = f"{PLURAL}.{GROUP}"
CORE_MANAGER = "notebook-controller"
PLATFORM_MANAGER = "platform-notebook-controller"


# ---------------------------------------------------------------------------
# CRD


def _tpu_spec_schema() -> dict:
    accelerators = sorted(ACCELERATORS) + sorted(_ALIASES)
    return {
        "type": "object",
        "required": ["accelerator", "topology"],
        "properties": {
            "accelerator": {
                "type": "string",
                "enum": accelerators,
                "description": "TPU generation (canonical name or GKE alias).",
            },
            "topology": {
                "type": "string",
                "pattern": r"^\d+x\d+(x\d+)?$",
                "description": "Chip grid, e.g. 4x4 (v5e/v6e) or 2x2x2 (v4/v5p).",
            },
            "runtimeVersion": {"type": "string"},
            "spot": {"type": "boolean"},
            "sliceCount": {
                "type": "integer",
                "minimum": 1,
                "default": 1,
                "description": (
                    "Number of identical slices forming one multislice "
                    "notebook (DCN between slices, ICI within)."
                ),
            },
        },
    }


def _tpu_status_schema() -> dict:
    return {
        "type": "object",
        "properties": {
            "hosts": {"type": "integer"},
            "readyHosts": {"type": "integer"},
            "sliceHealth": {
                "type": "string",
                "enum": ["Healthy", "Forming", "Interrupted", "Stopped"],
            },
            "acceleratorType": {"type": "string"},
            "jaxCoordinator": {"type": "string"},
            "profilingServer": {"type": "string"},
            "servingEndpoint": {"type": "string"},
            "slices": {"type": "integer"},
            "hostsPerSlice": {"type": "integer"},
        },
    }


def _notebook_schema() -> dict:
    """openAPIV3Schema for one served version.

    The reference inlines the full generated PodSpec schema
    (config/crd/bases/kubeflow.org_notebooks.yaml); a CRD generated from Go
    types gets that for free. Here the template keeps PodSpec as a
    preserve-unknown passthrough — same user contract (arbitrary PodSpec),
    no 20k-line vendored schema to drift.
    """
    return {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "template": {
                        "type": "object",
                        "properties": {
                            "spec": {
                                "type": "object",
                                "x-kubernetes-preserve-unknown-fields": True,
                            }
                        },
                    },
                    "tpu": _tpu_spec_schema(),
                },
            },
            "status": {
                "type": "object",
                "properties": {
                    "conditions": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "x-kubernetes-preserve-unknown-fields": True,
                        },
                    },
                    "readyReplicas": {"type": "integer"},
                    "containerState": {
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                    },
                    "tpu": _tpu_status_schema(),
                },
            },
        },
    }


def notebook_crd() -> dict:
    """The Notebook CRD: three served versions, v1beta1 storage (the
    conversion hub — reference api/v1beta1/notebook_conversion.go:19)."""
    versions = []
    for v in VERSIONS:
        versions.append(
            {
                "name": v,
                "served": True,
                "storage": v == "v1beta1",
                "schema": {"openAPIV3Schema": _notebook_schema()},
                "subresources": {"status": {}},
                "additionalPrinterColumns": [
                    {
                        "name": "Ready",
                        "type": "integer",
                        "jsonPath": ".status.readyReplicas",
                    },
                    {
                        "name": "TPU",
                        "type": "string",
                        "jsonPath": ".spec.tpu.accelerator",
                    },
                    {
                        "name": "Topology",
                        "type": "string",
                        "jsonPath": ".spec.tpu.topology",
                    },
                ],
            }
        )
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": CRD_NAME},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": KIND,
                "listKind": f"{KIND}List",
                "plural": PLURAL,
                "singular": "notebook",
            },
            "scope": "Namespaced",
            "conversion": {"strategy": "None"},
            "versions": versions,
        },
    }


def slicepool_crd() -> dict:
    """SlicePool CRD (warm slice capacity; kubeflow_tpu.api.slicepool —
    TPU-native, no reference counterpart)."""
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["tpu"],
                "properties": {
                    "tpu": _tpu_spec_schema(),
                    "warmReplicas": {
                        "type": "integer",
                        "minimum": 0,
                        "default": 1,
                        "description": "Warm placeholder slices to maintain.",
                    },
                    "image": {
                        "type": "string",
                        "description": (
                            "Workbench image the placeholders keep pulled "
                            "on slice nodes."
                        ),
                    },
                    "autoscale": {
                        "type": "object",
                        "description": (
                            "Replaces warmReplicas with a demand-driven "
                            "target: min..max, +1 per claim miss, -1 per "
                            "idle scaleDownAfterSeconds."
                        ),
                        "properties": {
                            "min": {"type": "integer", "minimum": 0},
                            "max": {"type": "integer", "minimum": 0},
                            "scaleDownAfterSeconds": {
                                "type": "integer",
                                "minimum": 1,
                                "default": 600,
                            },
                        },
                    },
                },
            },
            "status": {
                "type": "object",
                "properties": {
                    "generation": {"type": "integer"},
                    "warmReplicas": {"type": "integer"},
                    "readyReplicas": {"type": "integer"},
                    "autoscaleTarget": {"type": "integer"},
                    "lastScaleTime": {"type": "number"},
                    "missCountSeen": {"type": "integer"},
                    "conditions": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "x-kubernetes-preserve-unknown-fields": True,
                        },
                    },
                },
            },
        },
    }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"slicepools.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": "SlicePool",
                "listKind": "SlicePoolList",
                "plural": "slicepools",
                "singular": "slicepool",
            },
            "scope": "Namespaced",
            "conversion": {"strategy": "None"},
            "versions": [
                {
                    "name": "v1",
                    "served": True,
                    "storage": True,
                    "schema": {"openAPIV3Schema": schema},
                    "subresources": {"status": {}},
                    "additionalPrinterColumns": [
                        {
                            "name": "Warm",
                            "type": "integer",
                            "jsonPath": ".status.warmReplicas",
                        },
                        {
                            "name": "Ready",
                            "type": "integer",
                            "jsonPath": ".status.readyReplicas",
                        },
                        {
                            "name": "Topology",
                            "type": "string",
                            "jsonPath": ".spec.tpu.topology",
                        },
                    ],
                }
            ],
        },
    }


def placeholder_priority_class() -> dict:
    """Negative priority for SlicePool placeholder pods: any
    default-priority notebook pod preempts them, so a pool refill racing a
    claiming notebook for the just-freed slice nodes always loses
    (kubeflow_tpu.controller.slicepool)."""
    return {
        "apiVersion": "scheduling.k8s.io/v1",
        "kind": "PriorityClass",
        "metadata": {"name": "tpu-slicepool-placeholder"},
        "value": -100,
        "globalDefault": False,
        "description": (
            "Warm TPU slice placeholders; preempted by notebook workloads."
        ),
    }


def sample_slicepool() -> dict:
    return {
        "apiVersion": f"{GROUP}/v1",
        "kind": "SlicePool",
        "metadata": {"name": "v5e-16-warm", "namespace": "default"},
        "spec": {
            "tpu": {"accelerator": "v5e", "topology": "4x4"},
            "warmReplicas": 1,
            "image": "jax-notebook:latest",
        },
    }


# ---------------------------------------------------------------------------
# RBAC


def _rule(api_groups, resources, verbs):
    # Copies, not references: shared verb lists (_READ) must not alias
    # across rules — aliasing emits YAML anchors into the rendered RBAC
    # and lets a mutation of one rule's verbs corrupt every other.
    return {
        "apiGroups": list(api_groups),
        "resources": list(resources),
        "verbs": list(verbs),
    }


_ALL = ["create", "delete", "get", "list", "patch", "update", "watch"]
_READ = ["get", "list", "watch"]


def core_cluster_role() -> dict:
    """Upstream controller RBAC (reference
    components/notebook-controller/config/rbac/role.yaml)."""
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": {"name": f"{CORE_MANAGER}-role"},
        "rules": [
            _rule([GROUP], [PLURAL], _ALL),
            _rule([GROUP], [f"{PLURAL}/status"], ["get", "patch", "update"]),
            _rule([GROUP], [f"{PLURAL}/finalizers"], ["update"]),
            # update/patch beyond read: the spawn path writes demand-signal
            # annotations on the SlicePool main resource (slicepool.py
            # _stamp / _clear_demand_annotations) — read-only verbs would
            # 403 every TPU notebook spawn in a namespace with an
            # autoscaled pool.
            _rule([GROUP], ["slicepools"], _READ + ["patch", "update"]),
            _rule([GROUP], ["slicepools/status"], ["get", "patch", "update"]),
            _rule(["apps"], ["statefulsets"], _ALL),
            _rule([""], ["services"], _ALL),
            # Istio serving mode (kubeflow overlay): the reconciler owns a
            # VirtualService per notebook (reference role.yaml
            # networking.istio.io rule).
            _rule(["networking.istio.io"], ["virtualservices"], _ALL),
            # "create": the ENABLE_IMAGE_PREPULL controller maintains
            # node-pinned pre-pull pods (controller/prepull.py); delete
            # also serves failed-slice pod recreation.
            _rule([""], ["pods"], _READ + ["create", "delete"]),
            _rule([""], ["events"], _READ + ["create", "patch"]),
            _rule([""], ["nodes"], _READ),
            # Pre-pull image set source (notebook-prepull-images).
            _rule([""], ["configmaps"], _READ),
            _rule(["coordination.k8s.io"], ["leases"], _ALL),
        ],
    }


def platform_cluster_role() -> dict:
    """Platform controller RBAC (reference
    components/odh-notebook-controller/config/rbac/role.yaml)."""
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": {"name": f"{PLATFORM_MANAGER}-role"},
        "rules": [
            _rule([GROUP], [PLURAL], _READ + ["patch", "update"]),
            _rule([GROUP], [f"{PLURAL}/finalizers"], ["update"]),
            _rule([""], ["serviceaccounts", "services", "configmaps", "secrets"], _ALL),
            _rule(["networking.k8s.io"], ["networkpolicies"], _ALL),
            _rule(["gateway.networking.k8s.io"], ["httproutes", "referencegrants"], _ALL),
            _rule(["gateway.networking.k8s.io"], ["gateways"], _READ),
            _rule(
                ["rbac.authorization.k8s.io"],
                ["rolebindings", "clusterrolebindings"],
                _ALL,
            ),
            _rule(["image.openshift.io"], ["imagestreams"], _READ),
            _rule(["config.openshift.io"], ["apiservers", "proxies"], _READ),
            _rule(["oauth.openshift.io"], ["oauthclients"], _READ + ["delete"]),
            _rule(
                ["datasciencepipelinesapplications.opendatahub.io"],
                ["datasciencepipelinesapplications"],
                _READ,
            ),
            _rule(["coordination.k8s.io"], ["leases"], _ALL),
            _rule([""], ["events"], ["create", "patch"]),
        ],
    }


def rbac_manifests(manager: str, cluster_role: dict) -> list[dict]:
    sa = {
        "apiVersion": "v1",
        "kind": "ServiceAccount",
        "metadata": {"name": manager, "namespace": "system"},
    }
    crb = {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": {"name": f"{manager}-rolebinding"},
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole",
            "name": cluster_role["metadata"]["name"],
        },
        "subjects": [
            {"kind": "ServiceAccount", "name": manager, "namespace": "system"}
        ],
    }
    return [sa, cluster_role, crb]


# ---------------------------------------------------------------------------
# Managers


def culler_config_map() -> dict:
    """Culler knobs as a ConfigMap (reference
    config/manager/manager.yaml:44-58 sources these env vars)."""
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": f"{CORE_MANAGER}-culler-config", "namespace": "system"},
        "data": {
            "ENABLE_CULLING": "false",
            "CULL_IDLE_TIME": "1440",
            "IDLENESS_CHECK_PERIOD": "1",
            "CLUSTER_DOMAIN": "cluster.local",
            # Dynamic per-TPU-node image pre-pull (controller/prepull.py);
            # the static image_prepuller_daemonset sample is the
            # controller-less alternative.
            "ENABLE_IMAGE_PREPULL": "false",
        },
    }


def core_manager_deployment() -> dict:
    """Core controller Deployment (reference config/manager/manager.yaml)."""
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": CORE_MANAGER, "namespace": "system"},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": CORE_MANAGER}},
            "template": {
                "metadata": {"labels": {"app": CORE_MANAGER}},
                "spec": {
                    "serviceAccountName": CORE_MANAGER,
                    "containers": [
                        {
                            "name": "manager",
                            "image": "kubeflow-tpu/notebook-controller:latest",
                            # :latest defaults pullPolicy to Always, which
                            # would bypass locally-loaded images (KinD e2e).
                            "imagePullPolicy": "IfNotPresent",
                            "command": ["python", "-m", "kubeflow_tpu.cmd.notebook_manager"],
                            "args": [
                                "--metrics-addr=:8080",
                                "--probe-addr=:8081",
                                "--enable-leader-election",
                            ],
                            "envFrom": [
                                {
                                    "configMapRef": {
                                        "name": f"{CORE_MANAGER}-culler-config"
                                    }
                                }
                            ],
                            "env": [
                                {
                                    "name": "K8S_NAMESPACE",
                                    "valueFrom": {
                                        "fieldRef": {"fieldPath": "metadata.namespace"}
                                    },
                                }
                            ],
                            "ports": [
                                {"containerPort": 8080, "name": "metrics"},
                                {"containerPort": 8081, "name": "probes"},
                            ],
                            "livenessProbe": {
                                "httpGet": {"path": "/healthz", "port": 8081}
                            },
                            "readinessProbe": {
                                "httpGet": {"path": "/readyz", "port": 8081}
                            },
                            "resources": {
                                "requests": {"cpu": "100m", "memory": "128Mi"},
                                "limits": {"cpu": "1", "memory": "512Mi"},
                            },
                        }
                    ],
                },
            },
        },
    }


def platform_manager_deployment() -> dict:
    """Platform controller Deployment with webhook server (reference odh
    config/manager + webhook serving-cert wiring)."""
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": PLATFORM_MANAGER, "namespace": "system"},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": PLATFORM_MANAGER}},
            "template": {
                "metadata": {"labels": {"app": PLATFORM_MANAGER}},
                "spec": {
                    "serviceAccountName": PLATFORM_MANAGER,
                    "containers": [
                        {
                            "name": "manager",
                            "image": "kubeflow-tpu/platform-notebook-controller:latest",
                            "imagePullPolicy": "IfNotPresent",
                            "command": ["python", "-m", "kubeflow_tpu.cmd.platform_manager"],
                            "args": [
                                "--kube-rbac-proxy-image=$(KUBE_RBAC_PROXY_IMAGE)",
                                "--webhook-port=8443",
                                "--cert-dir=/tmp/k8s-webhook-server/serving-certs",
                                "--enable-leader-election",
                            ],
                            "env": [
                                {
                                    "name": "KUBE_RBAC_PROXY_IMAGE",
                                    "value": "gcr.io/kubebuilder/kube-rbac-proxy:v0.16.0",
                                },
                                {
                                    "name": "K8S_NAMESPACE",
                                    "valueFrom": {
                                        "fieldRef": {"fieldPath": "metadata.namespace"}
                                    },
                                },
                            ],
                            "ports": [
                                {"containerPort": 8443, "name": "webhook"},
                                {"containerPort": 8080, "name": "metrics"},
                                {"containerPort": 8081, "name": "probes"},
                            ],
                            "livenessProbe": {
                                "httpGet": {"path": "/healthz", "port": 8081}
                            },
                            "readinessProbe": {
                                "httpGet": {"path": "/readyz", "port": 8081}
                            },
                            "volumeMounts": [
                                {
                                    "name": "cert",
                                    "mountPath": "/tmp/k8s-webhook-server/serving-certs",
                                    "readOnly": True,
                                }
                            ],
                            "resources": {
                                "requests": {"cpu": "100m", "memory": "256Mi"},
                                "limits": {"cpu": "1", "memory": "1Gi"},
                            },
                        }
                    ],
                    "volumes": [
                        {
                            "name": "cert",
                            "secret": {"secretName": "webhook-server-cert"},
                        }
                    ],
                },
            },
        },
    }


def webhook_service() -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": f"{PLATFORM_MANAGER}-webhook", "namespace": "system"},
        "spec": {
            "selector": {"app": PLATFORM_MANAGER},
            "ports": [{"port": 443, "targetPort": 8443}],
        },
    }


def webhook_configurations() -> list[dict]:
    """Mutating + validating webhook registrations (reference
    config/webhook/manifests.yaml: /mutate-notebook-v1, /validate-notebook-v1)."""
    rule = {
        "apiGroups": [GROUP],
        "apiVersions": list(VERSIONS),
        "operations": ["CREATE", "UPDATE"],
        "resources": [PLURAL],
    }
    client_config = lambda path: {  # noqa: E731
        "service": {
            "name": f"{PLATFORM_MANAGER}-webhook",
            "namespace": "system",
            "path": path,
        }
    }
    mutating = {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "MutatingWebhookConfiguration",
        "metadata": {"name": f"{PLATFORM_MANAGER}-mutating"},
        "webhooks": [
            {
                "name": f"mutate.{CRD_NAME}",
                "admissionReviewVersions": ["v1"],
                "clientConfig": client_config("/mutate-notebook-v1"),
                "rules": [rule],
                "sideEffects": "None",
                "failurePolicy": "Fail",
            }
        ],
    }
    validating = {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "ValidatingWebhookConfiguration",
        "metadata": {"name": f"{PLATFORM_MANAGER}-validating"},
        "webhooks": [
            {
                "name": f"validate.{CRD_NAME}",
                "admissionReviewVersions": ["v1"],
                "clientConfig": client_config("/validate-notebook-v1"),
                "rules": [rule],
                "sideEffects": "None",
                "failurePolicy": "Fail",
            }
        ],
    }
    return [mutating, validating]


# ---------------------------------------------------------------------------
# Samples


DEFAULT_PREPULL_IMAGES = ("jax-notebook:latest",)


def image_prepuller_daemonset(images=DEFAULT_PREPULL_IMAGES) -> dict:
    """DaemonSet that pre-pulls notebook images onto every TPU node.

    Image pull is the dominant variable cost in the <90s p50 spawn budget
    (BASELINE.md north star): multi-GB notebook images pulled at spawn
    time blow it on cold nodes. Each image runs as an initContainer that
    exits immediately; the pause main container keeps the pod (and the
    cached image layers) resident. Targets any node carrying the GKE TPU
    accelerator label via an Exists affinity.

    This is the STATIC sample (fixed image list, applied by the
    operator). ``ENABLE_IMAGE_PREPULL=true`` on the core manager runs
    the dynamic counterpart instead (controller/prepull.py): image set
    from the notebook-prepull-images ConfigMap UNION live TPU notebooks,
    rolled on change, failed pulls retried with backoff."""
    # A prepull container must exit 0 no matter what the target image
    # contains — distroless/scratch images ship NO binaries at all. The
    # standard warm-puller recipe: copy a static no-op binary out of
    # busybox into an emptyDir first, then run THAT from every target
    # image's filesystem.
    from kubeflow_tpu.controller.prepull import prepull_init_containers

    init = prepull_init_containers(images, name_prefix="prepull")
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {
            "name": "notebook-image-prepuller",
            "namespace": "system",
            "labels": {"app": "notebook-image-prepuller"},
        },
        "spec": {
            "selector": {"matchLabels": {"app": "notebook-image-prepuller"}},
            "updateStrategy": {"type": "RollingUpdate"},
            "template": {
                "metadata": {"labels": {"app": "notebook-image-prepuller"}},
                "spec": {
                    "affinity": {
                        "nodeAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": {
                                "nodeSelectorTerms": [
                                    {
                                        "matchExpressions": [
                                            {
                                                "key": "cloud.google.com/gke-tpu-accelerator",
                                                "operator": "Exists",
                                            }
                                        ]
                                    }
                                ]
                            }
                        }
                    },
                    "tolerations": [
                        {
                            "key": "google.com/tpu",
                            "operator": "Exists",
                            "effect": "NoSchedule",
                        }
                    ],
                    "volumes": [{"name": "prepull-tools", "emptyDir": {}}],
                    "initContainers": init,
                    "containers": [
                        {
                            "name": "pause",
                            "image": "registry.k8s.io/pause:3.9",
                            "resources": {
                                "limits": {"cpu": "10m", "memory": "16Mi"}
                            },
                        }
                    ],
                },
            },
        },
    }


def sample_cpu_notebook() -> dict:
    return {
        "apiVersion": f"{GROUP}/v1",
        "kind": KIND,
        "metadata": {"name": "sample-cpu-notebook", "namespace": "default"},
        "spec": {
            "template": {
                "spec": {
                    "containers": [
                        {
                            "name": "sample-cpu-notebook",
                            "image": "jupyter-minimal:latest",
                            "resources": {
                                "requests": {"cpu": "500m", "memory": "1Gi"}
                            },
                        }
                    ]
                }
            }
        },
    }


def sample_tpu_notebook() -> dict:
    """The BASELINE.json north-star shape: 4-host v5e-16 slice."""
    return {
        "apiVersion": f"{GROUP}/v1",
        "kind": KIND,
        "metadata": {
            "name": "sample-tpu-notebook",
            "namespace": "default",
            "annotations": {
                ann.INJECT_AUTH: "true",
                # 60s of SIGTERM grace for an emergency checkpoint; the
                # webhook projects TPU_CHECKPOINT_GRACE_S and sizes
                # terminationGracePeriodSeconds from this.
                ann.TPU_CHECKPOINT_GRACE: "60",
            },
        },
        "spec": {
            "template": {
                "spec": {
                    "containers": [
                        {
                            "name": "sample-tpu-notebook",
                            "image": "jax-notebook:latest",
                            "resources": {
                                "requests": {"cpu": "4", "memory": "16Gi"}
                            },
                        }
                    ]
                }
            },
            "tpu": {"accelerator": "v5e", "topology": "4x4"},
        },
    }


# ---------------------------------------------------------------------------
# Name-length guard shared with the controller


def max_notebook_name_length() -> int:
    return MAX_NAME_LENGTH


# ---------------------------------------------------------------------------
# Checkpoint grace-period sizing

# Headroom added on top of the annotation's emergency-save budget when
# sizing terminationGracePeriodSeconds: container runtime teardown, PVC
# flush, and the process's own shutdown hooks all eat into the kubelet's
# window, and the emergency save must get the WHOLE budget the user asked
# for — otherwise the webhook's env contract promises time the kubelet
# never grants.
CHECKPOINT_FLUSH_MARGIN_S = 30
# The Kubernetes default; used when no grace annotation is present.
DEFAULT_TERMINATION_GRACE_S = 30


def termination_grace_seconds(grace: "int | None") -> int:
    """terminationGracePeriodSeconds for a notebook pod whose emergency
    checkpoint budget is ``grace`` seconds (parse_checkpoint_grace output;
    None means the annotation is absent/invalid → Kubernetes default)."""
    if grace is None:
        return DEFAULT_TERMINATION_GRACE_S
    return int(grace) + CHECKPOINT_FLUSH_MARGIN_S
