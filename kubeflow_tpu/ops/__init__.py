from kubeflow_tpu.ops.attention import flash_attention  # noqa: F401
