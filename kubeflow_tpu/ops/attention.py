"""Attention ops: pallas flash attention for TPU, XLA fallback elsewhere.

The hot op of the model stack (SURVEY.md has no reference counterpart — the
reference is a control plane; this exists for the in-notebook Llama
benchmark parity target in BASELINE.md).

Design per /opt/skills/guides/pallas_guide.md:
- online-softmax flash attention, grid over (batch*heads, q blocks),
  K/V resident in VMEM per program (S·D·2·2 bytes ≪ 16 MB for bench
  shapes), fori_loop over K blocks with running (m, l, o) carries —
  no materialized S×S scores, HBM traffic stays O(S·D),
- MXU-shaped blocks (128 lanes), f32 accumulation via
  preferred_element_type, bf16 in/out,
- causal masking by block: fully-unmasked blocks skip the compare entirely.

Decode (q_len == 1) is bandwidth-bound over the KV cache and gains nothing
from pallas tiling here; it uses the XLA path which fuses into two GEVMs.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

# Import guard keeps CPU-only environments importable without TPU pallas.
try:  # pragma: no cover
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None

BLOCK_Q = 128  # minimum/alignment block; actual blocks picked per shape
BLOCK_K = 128
# Measured on v5e (S=2048/4096, H=32, D=128): 512-wide blocks run the
# kernel ~4x faster than 128 (19.9 → 77.8 TFLOP/s at S=2048) — bigger
# tiles amortize the softmax VPU work against MXU matmuls. Block choice
# is the largest candidate dividing the sequence, so shorter prompts
# still run (alignment minimum stays 128).
_BLOCK_CANDIDATES = (512, 256, 128)
NEG_INF = -1e30


def _pick_block(length: int) -> int:
    for cand in _BLOCK_CANDIDATES:
        if length % cand == 0:
            return cand
    return 0  # not 128-aligned → caller falls back to XLA


# Pluggable implementations: the parallel layer registers e.g. "ring"
# (sequence-parallel ring attention bound to a concrete mesh) here, so the
# model code stays mesh-agnostic.
_IMPL_REGISTRY: dict = {}


def register_attention_impl(name: str, fn) -> None:
    _IMPL_REGISTRY[name] = fn


def flash_attention(
    q: jax.Array,  # (B, H, Sq, D)
    k: jax.Array,  # (B, H, Sk, D)
    v: jax.Array,  # (B, H, Sk, D)
    causal: bool = True,
    q_offset: int = 0,
    impl: str = "auto",
    window: int = 0,
    kv_mask: "jax.Array | None" = None,
) -> jax.Array:
    """Multi-head attention. ``q_offset`` is q's global position offset
    relative to k (for cached prefill continuation). ``window`` > 0 adds
    sliding-window masking (Mistral-style: query at position p attends
    keys in (p-window, p]). ``kv_mask`` (B, Sk) bool marks VALID key
    positions — False keys (left-padding in batched serving) are masked
    for every query. ``impl`` may be a registered name or a callable with
    this same signature (mesh-bound impls like ring attention are passed
    directly so two meshes never fight over one registry name)."""
    if callable(impl) or impl in _IMPL_REGISTRY:
        if window or kv_mask is not None:
            raise NotImplementedError(
                "sequence-parallel attention impls do not support "
                "sliding windows / padding masks yet"
            )
        fn = impl if callable(impl) else _IMPL_REGISTRY[impl]
        return fn(q, k, v, causal=causal, q_offset=q_offset)
    if impl == "auto":
        impl = "pallas" if (kv_mask is None and _pallas_ok(q, k)) else "xla"
    if impl == "pallas":
        if kv_mask is not None:
            # Fail loudly: a silent XLA fallback would make explicit
            # pallas benchmarks/tests measure the wrong code path.
            raise NotImplementedError(
                "the pallas kernel does not support kv_mask; use "
                "impl='auto'/'xla' for padded batches"
            )
        return _flash_attention_pallas(
            q, k, v, causal=causal, q_offset=q_offset, window=window
        )
    return _attention_xla(
        q, k, v, causal=causal, q_offset=q_offset, window=window,
        kv_mask=kv_mask,
    )


def _pallas_ok(q: jax.Array, k: jax.Array) -> bool:
    if pl is None or jax.default_backend() not in ("tpu", "axon"):
        return False
    _, _, sq, d = q.shape
    sk = k.shape[2]
    return (
        _pick_block(sq) > 0 and _pick_block(sk) > 0
        and d % 128 == 0 and sq > 1
    )


# ---------------------------------------------------------------------------
# XLA reference path (CPU tests, decode, ragged shapes)


def _attention_xla(
    q, k, v, causal: bool, q_offset: int, window: int = 0, kv_mask=None
) -> jax.Array:
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal or window:
        sq, sk = q.shape[2], k.shape[2]
        q_pos = jnp.arange(sq)[:, None] + q_offset
        k_pos = jnp.arange(sk)[None, :]
        mask = k_pos <= q_pos if causal else jnp.ones((sq, sk), bool)
        if window:
            mask = mask & (k_pos > q_pos - window)
        scores = jnp.where(mask, scores, NEG_INF)
    if kv_mask is not None:
        scores = jnp.where(kv_mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas path


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, causal: bool, q_offset: int,
                  sk: int, scale: float, window: int = 0,
                  block_q: int = BLOCK_Q, block_k: int = BLOCK_K):
    # Block shapes: q (1, block_q, D); k/v (1, sk, D); o (1, block_q, D).
    qi = pl.program_id(1)
    q_block = q_ref[0].astype(jnp.float32) * scale  # (BQ, D)
    d = q_block.shape[-1]
    num_k_blocks = sk // block_k

    def body(kb, carry):
        m, l, o = carry
        k_block = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_block = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q_block, k_block.T, preferred_element_type=jnp.float32)
        if causal or window:
            q_pos = (
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                + qi * block_q
                + q_offset
            )
            k_pos = (
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
                + kb * block_k
            )
            mask = k_pos <= q_pos if causal else (k_pos == k_pos)
            if window:
                mask = mask & (k_pos > q_pos - window)
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[:, None] + jnp.dot(
            p, v_block, preferred_element_type=jnp.float32
        )
        return m_new, l_new, o_new

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    o0 = jnp.zeros((block_q, d), jnp.float32)

    if causal:
        # Blocks strictly above the diagonal contribute nothing; bound the
        # loop at the diagonal block (compile-time per q-block is not
        # possible — qi is dynamic — so bound dynamically).
        last = jnp.minimum(
            num_k_blocks,
            (qi * block_q + q_offset + block_q + block_k - 1) // block_k,
        )
    else:
        last = num_k_blocks
    if window:
        # Blocks entirely BELOW the window contribute nothing either: the
        # earliest visible key for this q block is q_start - window + 1.
        first = jnp.maximum(0, (qi * block_q + q_offset - window + 1) // block_k)
    else:
        first = 0
    m, l, o = jax.lax.fori_loop(first, last, body, (m0, l0, o0))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_attention_pallas(
    q, k, v, causal: bool, q_offset: int, window: int = 0
) -> jax.Array:
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    block_q = _pick_block(sq)
    block_k = _pick_block(sk)
    if not block_q or not block_k:
        raise ValueError(
            f"pallas flash attention needs 128-aligned sequence lengths, "
            f"got sq={sq}, sk={sk}; use impl='auto'/'xla'"
        )
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    grid = (b * h, sq // block_q)
    kernel = functools.partial(
        _flash_kernel, causal=causal, q_offset=q_offset, sk=sk, scale=scale,
        window=window, block_q=block_q, block_k=block_k,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d)
