"""Attention ops: pallas flash attention for TPU, XLA fallback elsewhere.

The hot op of the model stack (SURVEY.md has no reference counterpart — the
reference is a control plane; this exists for the in-notebook Llama
benchmark parity target in BASELINE.md).

Design per /opt/skills/guides/pallas_guide.md:
- **streamed K/V**: the grid is (batch*heads, q blocks, k blocks) with the
  k-block dimension innermost; Pallas's pipeline machinery double-buffers
  the K/V block fetches against compute, so VMEM holds only O(block) state
  and sequence length is bounded by HBM, not VMEM (the previous design held
  the full K/V per program in VMEM, capping S and MFU),
- online-softmax flash recursion carried in f32 VMEM scratch (m, l, acc)
  across the k-block grid steps; output written once on the last k step,
- **fetch skipping**: causal/windowed blocks that contribute nothing are
  skipped by clamping the K/V BlockSpec index map to the nearest needed
  block — Pallas elides refetches when the block index is unchanged, so
  masked-out blocks cost neither HBM bandwidth nor MXU flops,
- boundary-only masking: interior blocks skip the iota/compare/select
  entirely; only blocks straddling the causal diagonal or window edge pay
  the VPU masking cost,
- MXU-shaped blocks (multiples of 128 lanes), f32 accumulation via
  preferred_element_type, bf16 in/out,
- **differentiable**: custom_vjp with two pallas backward kernels (dq, and
  dk/dv) using the saved logsumexp — flash attention's standard backward —
  so TPU training steps run the pallas path end to end.

Decode (q_len == 1) is bandwidth-bound over the KV cache and gains nothing
from pallas tiling here; it uses the XLA path which fuses into two GEVMs.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

# Import guard keeps CPU-only environments importable without TPU pallas.
try:  # pragma: no cover
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None

BLOCK_Q = 128  # minimum/alignment block; actual blocks picked per shape
BLOCK_K = 128
# Measured on v5e (H=32, D=128, causal): at S>=4096, 1024-wide blocks beat
# 512 by ~40-60% (63.6 vs 39.5 TFLOP/s at S=8192) — fewer k-steps means
# fewer online-softmax rescales and cross-lane reductions per score
# element, which (not the MXU dots) bound the forward. At S=2048 the grid
# is too small to pipeline 1024-wide blocks and 512 wins. Block choice is
# the largest eligible candidate dividing the sequence, so shorter prompts
# still run (alignment minimum stays 128).
_BLOCK_CANDIDATES = (1024, 512, 256, 128)
NEG_INF = -1e30


def _pick_block(length: int) -> int:
    for cand in _BLOCK_CANDIDATES:
        if cand == 1024 and length < 4096:
            continue  # small grids pipeline better with 512-wide blocks
        if length % cand == 0:
            return cand
    return 0  # not 128-aligned → caller falls back to XLA


# Pluggable implementations: the parallel layer registers e.g. "ring"
# (sequence-parallel ring attention bound to a concrete mesh) here, so the
# model code stays mesh-agnostic.
_IMPL_REGISTRY: dict = {}


def register_attention_impl(name: str, fn) -> None:
    _IMPL_REGISTRY[name] = fn


def flash_attention(
    q: jax.Array,  # (B, H, Sq, D)
    k: jax.Array,  # (B, H, Sk, D)
    v: jax.Array,  # (B, H, Sk, D)
    causal: bool = True,
    q_offset: int = 0,
    impl: str = "auto",
    window: int = 0,
    kv_mask: "jax.Array | None" = None,
) -> jax.Array:
    """Multi-head attention. ``q_offset`` is q's global position offset
    relative to k (for cached prefill continuation). ``window`` > 0 adds
    sliding-window masking (Mistral-style: query at position p attends
    keys in (p-window, p]). ``kv_mask`` (B, Sk) bool marks VALID key
    positions — False keys (left-padding in batched serving) are masked
    for every query. ``impl`` may be a registered name or a callable with
    this same signature (mesh-bound impls like ring attention are passed
    directly so two meshes never fight over one registry name).

    GQA: k/v may carry FEWER heads than q (H % Hkv == 0). The pallas
    kernel reads the unrepeated K/V directly (its index maps fold the
    group factor), so no rep-times-larger K/V buffer is ever materialized
    — the difference between fitting and OOMing a long-context GQA
    prefill. The XLA path and SP impls receive broadcast K/V instead.
    """
    h, hkv = q.shape[1], k.shape[1]
    if h != hkv and h % hkv != 0:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    if callable(impl) or impl in _IMPL_REGISTRY:
        fn = impl if callable(impl) else _IMPL_REGISTRY[impl]
        if h != hkv:  # SP impls shard the head axis; give them full heads
            k = _broadcast_kv(k, h // hkv)
            v = _broadcast_kv(v, h // hkv)
        return fn(
            q, k, v, causal=causal, q_offset=q_offset, window=window,
            kv_mask=kv_mask,
        )
    if impl == "auto":
        impl = "pallas" if _pallas_ok(q, k) else "xla"
    if impl == "pallas":
        return _flash_attention_pallas(
            q, k, v, causal, q_offset, window, kv_mask=kv_mask
        )
    if h != hkv:
        k = _broadcast_kv(k, h // hkv)
        v = _broadcast_kv(v, h // hkv)
    return _attention_xla(
        q, k, v, causal=causal, q_offset=q_offset, window=window,
        kv_mask=kv_mask,
    )


def _broadcast_kv(x: jax.Array, rep: int) -> jax.Array:
    b, hkv, s, d = x.shape
    return jnp.broadcast_to(x[:, :, None], (b, hkv, rep, s, d)).reshape(
        b, hkv * rep, s, d
    )


# Kill switch for the pallas path: set by force_xla_fallback() or the
# KUBEFLOW_TPU_FORCE_XLA_ATTENTION env var. Exists so a kernel-lowering
# regression can never take the whole model stack down — impl="auto"
# callers degrade to the XLA path instead.
import os as _os

_FORCE_XLA = _os.environ.get("KUBEFLOW_TPU_FORCE_XLA_ATTENTION", "") == "1"


def force_xla_fallback(enabled: bool = True) -> None:
    """Make impl="auto" resolve to the XLA path process-wide. NOTE: jitted
    programs already traced keep their compiled choice; call before the
    first trace (bench.py uses this to retry a failed config)."""
    global _FORCE_XLA
    _FORCE_XLA = enabled


def _pallas_ok(q: jax.Array, k: jax.Array) -> bool:
    if _FORCE_XLA or pl is None or jax.default_backend() not in ("tpu", "axon"):
        return False
    _, _, sq, d = q.shape
    sk = k.shape[2]
    return (
        _pick_block(sq) > 0 and _pick_block(sk) > 0
        and d % 128 == 0 and sq > 1
    )


# ---------------------------------------------------------------------------
# XLA reference path (CPU tests, decode, ragged shapes)


def _attention_xla(
    q, k, v, causal: bool, q_offset: int, window: int = 0, kv_mask=None
) -> jax.Array:
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    visible = None  # (B?, 1, Sq|1, Sk) combined visibility
    if causal or window:
        sq, sk = q.shape[2], k.shape[2]
        q_pos = jnp.arange(sq)[:, None] + q_offset
        k_pos = jnp.arange(sk)[None, :]
        mask = k_pos <= q_pos if causal else jnp.ones((sq, sk), bool)
        if window:
            mask = mask & (k_pos > q_pos - window)
        scores = jnp.where(mask, scores, NEG_INF)
        visible = mask[None, None]
    if kv_mask is not None:
        kvm = kv_mask[:, None, None, :]
        scores = jnp.where(kvm, scores, NEG_INF)
        visible = kvm if visible is None else (visible & kvm)
    probs = jax.nn.softmax(scores, axis=-1)
    if visible is not None:
        # Safe-softmax convention shared with the pallas kernel: a row with
        # NO visible keys (left-padding ahead of the causal frontier)
        # contributes zero output and zero gradient, instead of the
        # uniform-softmax garbage plain softmax yields at -1e30.
        row_has_keys = jnp.any(visible, axis=-1, keepdims=True)
        probs = jnp.where(row_has_keys, probs, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas forward: streamed K/V, (bh, n_q, n_k) grid, k innermost.

_LANES = 128  # f32 scratch rows are lane-replicated to the native tile width

# Whole-KV forward variant: at short/medium S the streamed grid's per-step
# programs are ~1 µs of compute (a 512×512×128 dot) and grid dispatch
# overhead dominates — measured 8.4 TF/s at S=2048 vs 19.2 for the old
# whole-VMEM design. When K+V for one kv row fit comfortably in VMEM
# (~16 MB/core), fetch them ONCE per (batch·kv_head) row on a (bh, n_q)
# grid and run the k loop UNROLLED inside the kernel: same online-softmax
# math, same fetch-skipping (pl.when on not-needed chunks) and
# boundary-only masking, zero inter-step grid overhead. Streaming remains
# the long-S path (bounded VMEM). Threshold bytes = K+V combined, bf16.
_WHOLE_KV_MAX_BYTES = 4 * 1024 * 1024


def _whole_kv_ok(sk: int, d: int, itemsize: int) -> bool:
    return 2 * sk * d * itemsize <= _WHOLE_KV_MAX_BYTES


def _fwd_whole_kernel(
    q_ref, k_ref, v_ref, *rest,
    causal: bool, q_offset: int, window: int, scale: float,
    block_q: int, block_k: int, sk: int, with_mask: bool = False,
):
    """Single-fetch forward: K/V (and the serving kv_mask) are resident for
    the whole program; the k loop is a python-unrolled sequence of
    pl.when-guarded online-softmax updates against static VMEM slices."""
    if with_mask:
        mask_ref, o_ref, lse_ref, acc_scr, m_scr, l_scr = rest
    else:
        mask_ref = None
        o_ref, lse_ref, acc_scr, m_scr, l_scr = rest
    qi = pl.program_id(1)
    q_start = qi * block_q + q_offset

    m_scr[...] = jnp.full_like(m_scr, NEG_INF)
    l_scr[...] = jnp.zeros_like(l_scr)
    acc_scr[...] = jnp.zeros_like(acc_scr)

    def _step_at(k_start: int):
        def _step(mask):
            s = _block_scores(
                q_ref[0], k_ref[0, k_start:k_start + block_k, :], scale,
                valid_row=(
                    mask_ref[0, 0, k_start:k_start + block_k][None, :]
                    if mask_ref is not None else None
                ),
            )
            if mask is not None:
                s = jnp.where(mask, s, NEG_INF)
            _online_update(acc_scr, m_scr, l_scr, s,
                           v_ref[0, k_start:k_start + block_k, :])
        return _step

    for ki in range(sk // block_k):
        k_start = ki * block_k
        _guarded_chunk_step(q_start, k_start, block_q, block_k, causal,
                            window, _step_at(k_start))

    _flush_output(o_ref, lse_ref, acc_scr, m_scr, l_scr)


def _fwd_whole_call(
    qf, kf, vf, causal, q_offset, window, block_q, block_k, interpret=False,
    kv_mask8=None, heads=1, kv_heads=1,
):
    bh, sq, d = qf.shape
    sk = kf.shape[1]
    scale = 1.0 / math.sqrt(d)
    n_q = sq // block_q

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, qi: (i, qi, 0),
                     memory_space=pltpu.VMEM),
        # Full K/V rows, fetched once per bh row: the index map ignores qi,
        # so Pallas elides the refetch across this row's q blocks.
        pl.BlockSpec((1, sk, d),
                     lambda i, qi: (_kv_row(i, heads, kv_heads), 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, sk, d),
                     lambda i, qi: (_kv_row(i, heads, kv_heads), 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [qf, kf, vf]
    if kv_mask8 is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, sk), lambda i, qi: (i // heads, 0, 0),
                         memory_space=pltpu.VMEM)
        )
        args.append(kv_mask8)

    kernel = functools.partial(
        _fwd_whole_kernel, causal=causal, q_offset=q_offset, window=window,
        scale=scale, block_q=block_q, block_k=block_k, sk=sk,
        with_mask=kv_mask8 is not None,
    )
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((bh, sq, d), qf.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ),
        grid=(bh, n_q),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda i, qi: (i, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda i, qi: (i, 0, qi),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(*args)
    return out, lse[:, 0, :]


def _mask_bounds(causal: bool, window: int, block_q: int, block_k: int):
    """Return (first_k, last_k) BlockSpec index-map helpers bounding which
    k blocks contribute to a given q block (functions of the dynamic
    q-block index and static q_offset). Used to CLAMP the K/V index maps:
    Pallas elides refetches when a block index repeats, so out-of-bounds
    blocks cost no HBM bandwidth."""

    def first_k(qi, q_offset):
        if not window:
            return 0
        # Earliest visible key for this q block: q_start - window + 1.
        return jnp.maximum(0, (qi * block_q + q_offset - window + 1) // block_k)

    def last_k(qi, q_offset, n_k):
        if not causal:
            return n_k - 1
        # Last k block intersecting the causal diagonal for this q block.
        return jnp.minimum(
            n_k - 1, (qi * block_q + q_offset + block_q - 1) // block_k
        )

    return first_k, last_k


def _block_mask(q_start, k_start, block_q: int, block_k: int,
                causal: bool, window: int):
    """(BQ, BK) bool mask for one score block — the single definition the
    forward and both backward kernels share, so mask semantics cannot
    silently diverge between passes."""
    q_pos = (
        jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + q_start
    )
    k_pos = (
        jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + k_start
    )
    mask = k_pos <= q_pos if causal else (k_pos == k_pos)
    if window:
        mask = mask & (k_pos > q_pos - window)
    return mask


def _block_straddles(q_start, k_start, block_q: int, block_k: int,
                     causal: bool, window: int):
    """Scalar bool: does this (q, k) block pair straddle a mask edge?
    Interior blocks (fully visible) skip the iota/compare/select."""
    straddle = jnp.asarray(False)
    if causal:
        straddle = straddle | (k_start + block_k - 1 > q_start)
    if window:
        straddle = straddle | (k_start <= q_start + block_q - 1 - window)
    return straddle


# --- Flash-recursion math shared by the streamed and whole-KV forward
# kernels (the single definition, like _block_mask, so a numerics fix
# cannot silently diverge the two variants) ---


def _block_scores(q_blk, k_blk, scale: float, valid_row=None):
    """(BQ, BK) f32 scores: bf16 operands into the MXU (f32 operands would
    run the systolic array at ~1/4 rate), f32 accumulate+scale.
    ``valid_row`` is the serving kv_mask's (BK,)-broadcastable int8 row."""
    s = jnp.dot(q_blk, k_blk.T, preferred_element_type=jnp.float32) * scale
    if valid_row is not None:
        s = jnp.where(valid_row != 0, s, NEG_INF)
    return s


def _online_update(acc_scr, m_scr, l_scr, s_masked, v_blk):
    """One online-softmax accumulation step into the f32 VMEM scratch."""
    m_prev = m_scr[:, :1]  # (BQ, 1), lane-replicated store below
    l_prev = l_scr[:, :1]
    m_cur = jnp.max(s_masked, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s_masked - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
        p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
    )
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)


def _flush_output(o_ref, lse_ref, acc_scr, m_scr, l_scr):
    """Final normalize + write. Safe softmax: a row whose every key was
    masked (m still -inf) outputs ZERO, matching the XLA path; its lse
    stays ~NEG_INF, which the backward kernels key off to zero its
    grads."""
    l = l_scr[:, :1]
    m = m_scr[:, :1]
    out = acc_scr[...] / jnp.maximum(l, 1e-30)
    o_ref[0] = jnp.where(m > NEG_INF * 0.5, out, 0.0).astype(o_ref.dtype)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    lse_ref[0] = jnp.broadcast_to(lse.T, lse_ref.shape[1:])


def _guarded_chunk_step(q_start, k_start, block_q: int, block_k: int,
                        causal: bool, window: int, step):
    """Dispatch one (q, k) block with fetch-skipping and boundary-only
    masking: ``step(mask_or_None)`` runs only when the block contributes,
    and receives the (BQ, BK) position mask only when the block straddles
    a mask edge — interior blocks skip the iota/compare/select."""
    needed = jnp.asarray(True)
    if causal:
        needed = needed & (k_start <= q_start + block_q - 1)
    if window:
        needed = needed & (k_start + block_k - 1 > q_start - window)
    if not (causal or window):
        pl.when(needed)(lambda: step(None))
        return
    straddle = _block_straddles(q_start, k_start, block_q, block_k,
                                causal, window)
    pl.when(needed & straddle)(
        lambda: step(
            _block_mask(q_start, k_start, block_q, block_k, causal, window)
        )
    )
    pl.when(needed & ~straddle)(lambda: step(None))


def _fwd_kernel(
    q_ref, k_ref, v_ref, *rest,
    causal: bool, q_offset: int, window: int, scale: float,
    block_q: int, block_k: int, with_mask: bool = False,
):
    if with_mask:
        mask_ref, o_ref, lse_ref, acc_scr, m_scr, l_scr = rest
    else:
        mask_ref = None
        o_ref, lse_ref, acc_scr, m_scr, l_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q + q_offset
    k_start = ki * block_k

    def _step(mask):
        s = _block_scores(
            q_ref[0], k_ref[0], scale,
            valid_row=mask_ref[0] if mask_ref is not None else None,
        )
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        _online_update(acc_scr, m_scr, l_scr, s, v_ref[0])

    _guarded_chunk_step(q_start, k_start, block_q, block_k, causal, window,
                        _step)

    @pl.when(ki == n_k - 1)
    def _flush():
        _flush_output(o_ref, lse_ref, acc_scr, m_scr, l_scr)


def _kv_row(i, heads: int, kv_heads: int):
    """Map a flattened (batch*q_heads) grid row to its (batch*kv_heads)
    K/V row — the GQA group fold (identity when heads == kv_heads)."""
    if heads == kv_heads:
        return i
    rep = heads // kv_heads
    return (i // heads) * kv_heads + (i % heads) // rep


def _fwd_pallas_call(
    qf, kf, vf, causal, q_offset, window, block_q, block_k, interpret=False,
    kv_mask8=None, heads=1, kv_heads=1,
):
    bh, sq, d = qf.shape
    sk = kf.shape[1]
    if _whole_kv_ok(sk, d, kf.dtype.itemsize):
        return _fwd_whole_call(
            qf, kf, vf, causal, q_offset, window, block_q, block_k,
            interpret, kv_mask8=kv_mask8, heads=heads, kv_heads=kv_heads,
        )
    scale = 1.0 / math.sqrt(d)
    n_q, n_k = sq // block_q, sk // block_k
    first_k, last_k = _mask_bounds(causal, window, block_q, block_k)

    def kv_index(i, qi, ki):
        # Clamp the k-block index into this q block's needed range: skipped
        # blocks repeat the previous index, and Pallas elides the refetch.
        kidx = ki
        if causal:
            kidx = jnp.minimum(kidx, last_k(qi, q_offset, n_k))
        if window:
            kidx = jnp.maximum(kidx, first_k(qi, q_offset))
        return (_kv_row(i, heads, kv_heads), kidx, 0)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, qi, ki: (i, qi, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), kv_index, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), kv_index, memory_space=pltpu.VMEM),
    ]
    args = [qf, kf, vf]
    if kv_mask8 is not None:
        # (B, 1, Sk) int8 validity; one row per BATCH element (the bh grid
        # index folds heads, so divide back out).
        in_specs.append(
            pl.BlockSpec(
                (1, 1, block_k),
                lambda i, qi, ki: (i // heads, 0, kv_index(i, qi, ki)[1]),
                memory_space=pltpu.VMEM,
            )
        )
        args.append(kv_mask8)

    kernel = functools.partial(
        _fwd_kernel, causal=causal, q_offset=q_offset, window=window,
        scale=scale, block_q=block_q, block_k=block_k,
        with_mask=kv_mask8 is not None,
    )
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((bh, sq, d), qf.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ),
        grid=(bh, n_q, n_k),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda i, qi, ki: (i, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda i, qi, ki: (i, 0, qi),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    return out, lse[:, 0, :]


# ---------------------------------------------------------------------------
# Pallas backward: two streamed kernels sharing the saved logsumexp.
#
# Standard flash backward with delta = rowsum(dO ⊙ O):
#   p  = exp(q·kᵀ·scale − lse)
#   dv = pᵀ · dO
#   dp = dO · vᵀ
#   ds = p ⊙ (dp − delta)
#   dq = ds · k · scale        (accumulated over k blocks; q-block grid)
#   dk = dsᵀ · q · scale       (accumulated over q blocks; k-block grid)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    causal: bool, q_offset: int, window: int, scale: float,
    block_q: int, block_k: int, with_mask: bool = False,
):
    if with_mask:
        mask_ref, dq_ref, acc_scr = rest
    else:
        mask_ref = None
        dq_ref, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q + q_offset
    k_start = ki * block_k
    needed = jnp.asarray(True)
    if causal:
        needed = needed & (k_start <= q_start + block_q - 1)
    if window:
        needed = needed & (k_start + block_k - 1 > q_start - window)

    def _step(masked: bool):
        # bf16 MXU dots, f32 accumulation (f32 operands quarter the rate).
        s = jnp.dot(
            q_ref[0], k_ref[0].T, preferred_element_type=jnp.float32
        ) * scale
        if mask_ref is not None:
            s = jnp.where(mask_ref[0] != 0, s, NEG_INF)
        if masked:
            mask = _block_mask(
                q_start, k_start, block_q, block_k, causal, window
            )
            s = jnp.where(mask, s, NEG_INF)
        lse = lse_ref[0, 0][:, None]  # (BQ, 1)
        # Degenerate rows (no visible keys → lse ~ NEG_INF) get zero
        # gradients; at lse magnitudes of 1e30, exp(s - lse) can no longer
        # tell masked entries (-1e30) from real ones, so guard explicitly.
        p = jnp.where(lse > NEG_INF * 0.5, jnp.exp(s - lse), 0.0)
        dp = jnp.dot(
            do_ref[0], v_ref[0].T, preferred_element_type=jnp.float32
        )
        delta = delta_ref[0, 0][:, None]
        ds = p * (dp - delta)
        acc_scr[...] += jnp.dot(
            ds.astype(k_ref.dtype), k_ref[0],
            preferred_element_type=jnp.float32,
        ) * scale

    if not (causal or window):
        pl.when(needed)(functools.partial(_step, False))
    else:
        straddle = _block_straddles(
            q_start, k_start, block_q, block_k, causal, window
        )
        pl.when(needed & straddle)(functools.partial(_step, True))
        pl.when(needed & ~straddle)(functools.partial(_step, False))

    @pl.when(ki == n_k - 1)
    def _flush():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    causal: bool, q_offset: int, window: int, scale: float,
    block_q: int, block_k: int, with_mask: bool = False, n_q: int = 0,
):
    if with_mask:
        mask_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        mask_ref = None
        dk_ref, dv_ref, dk_scr, dv_scr = rest
    ki = pl.program_id(1)
    # Innermost dim sweeps (GQA group member, q block); only the q-block
    # part positions the mask — every group member shares positions.
    j = pl.program_id(2)
    n_j = pl.num_programs(2)
    qi = j % n_q

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q_start = qi * block_q + q_offset
    k_start = ki * block_k
    needed = jnp.asarray(True)
    if causal:
        needed = needed & (q_start + block_q - 1 >= k_start)
    if window:
        needed = needed & (q_start < k_start + block_k + window)

    def _step(masked: bool):
        # bf16 MXU dots, f32 accumulation (f32 operands quarter the rate).
        s = jnp.dot(
            q_ref[0], k_ref[0].T, preferred_element_type=jnp.float32
        ) * scale
        if mask_ref is not None:
            s = jnp.where(mask_ref[0] != 0, s, NEG_INF)
        if masked:
            mask = _block_mask(
                q_start, k_start, block_q, block_k, causal, window
            )
            s = jnp.where(mask, s, NEG_INF)
        lse = lse_ref[0, 0][:, None]
        # Same degenerate-row guard as the dq kernel.
        p = jnp.where(lse > NEG_INF * 0.5, jnp.exp(s - lse), 0.0)  # (BQ, BK)
        dv_scr[...] += jnp.dot(
            p.T.astype(do_ref.dtype), do_ref[0],
            preferred_element_type=jnp.float32,
        )
        dp = jnp.dot(
            do_ref[0], v_ref[0].T, preferred_element_type=jnp.float32
        )
        delta = delta_ref[0, 0][:, None]
        ds = p * (dp - delta)  # (BQ, BK)
        dk_scr[...] += jnp.dot(
            ds.T.astype(q_ref.dtype), q_ref[0],
            preferred_element_type=jnp.float32,
        ) * scale

    if not (causal or window):
        pl.when(needed)(functools.partial(_step, False))
    else:
        straddle = _block_straddles(
            q_start, k_start, block_q, block_k, causal, window
        )
        pl.when(needed & straddle)(functools.partial(_step, True))
        pl.when(needed & ~straddle)(functools.partial(_step, False))

    @pl.when(j == n_j - 1)
    def _flush():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_pallas_call(
    qf, kf, vf, do, lse, delta, causal, q_offset, window,
    block_q, block_k, interpret=False, kv_mask8=None, heads=1, kv_heads=1,
):
    bh, sq, d = qf.shape
    sk = kf.shape[1]
    scale = 1.0 / math.sqrt(d)
    n_q, n_k = sq // block_q, sk // block_k
    rep = heads // kv_heads
    first_k, last_k = _mask_bounds(causal, window, block_q, block_k)
    lse3 = lse[:, None, :]
    delta3 = delta[:, None, :]
    with_mask = kv_mask8 is not None

    def kv_index(i, qi, ki):
        kidx = ki
        if causal:
            kidx = jnp.minimum(kidx, last_k(qi, q_offset, n_k))
        if window:
            kidx = jnp.maximum(kidx, first_k(qi, q_offset))
        return (_kv_row(i, heads, kv_heads), kidx, 0)

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, qi, ki: (i, qi, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), kv_index, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), kv_index, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, d), lambda i, qi, ki: (i, qi, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_q), lambda i, qi, ki: (i, 0, qi),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_q), lambda i, qi, ki: (i, 0, qi),
                     memory_space=pltpu.VMEM),
    ]
    dq_args = [qf, kf, vf, do, lse3, delta3]
    if with_mask:
        dq_in_specs.append(
            pl.BlockSpec(
                (1, 1, block_k),
                lambda i, qi, ki: (i // heads, 0, kv_index(i, qi, ki)[1]),
                memory_space=pltpu.VMEM,
            )
        )
        dq_args.append(kv_mask8)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal=causal, q_offset=q_offset, window=window,
            scale=scale, block_q=block_q, block_k=block_k,
            with_mask=with_mask,
        ),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), qf.dtype),
        grid=(bh, n_q, n_k),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, qi, ki: (i, qi, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*dq_args)

    # dk/dv grid runs over KV rows; the innermost dimension sweeps
    # (rep × q-blocks) so each kv head accumulates its whole q-head GROUP
    # into one scratch before the flush — the GQA reduction happens inside
    # the kernel instead of over a rep-times-materialized K/V.
    def _decode_j(j):
        return j // n_q, j % n_q  # (which q head in the group, q block)

    def q_index(i, ki, j):
        r, qi = _decode_j(j)
        # Mirror of kv_index: clamp the q-block index to this k block's
        # contributing range so masked-out q blocks are never fetched.
        qidx = qi
        if causal:
            qidx = jnp.maximum(qidx, (ki * block_k - q_offset) // block_q)
        if window:
            qidx = jnp.minimum(
                qidx,
                jnp.maximum(
                    0,
                    (ki * block_k + block_k - 1 + window - 1 - q_offset)
                    // block_q,
                ),
            )
        q_row = (i // kv_heads) * heads + (i % kv_heads) * rep + r
        return (q_row, jnp.clip(qidx, 0, n_q - 1), 0)

    def q_row_index(i, ki, j):
        idx = q_index(i, ki, j)
        return (idx[0], 0, idx[1])

    bhkv = kf.shape[0]
    dkv_in_specs = [
        pl.BlockSpec((1, block_q, d), q_index, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, ki, j: (i, ki, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, ki, j: (i, ki, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, d), q_index, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_q), q_row_index, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_q), q_row_index, memory_space=pltpu.VMEM),
    ]
    dkv_args = [qf, kf, vf, do, lse3, delta3]
    if with_mask:
        dkv_in_specs.append(
            pl.BlockSpec(
                (1, 1, block_k), lambda i, ki, j: (i // kv_heads, 0, ki),
                memory_space=pltpu.VMEM,
            )
        )
        dkv_args.append(kv_mask8)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, q_offset=q_offset, window=window,
            scale=scale, block_q=block_q, block_k=block_k,
            with_mask=with_mask, n_q=n_q,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bhkv, sk, d), kf.dtype),
            jax.ShapeDtypeStruct((bhkv, sk, d), vf.dtype),
        ),
        grid=(bhkv, n_k, rep * n_q),
        in_specs=dkv_in_specs,
        out_specs=(
            pl.BlockSpec((1, block_k, d), lambda i, ki, j: (i, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, ki, j: (i, ki, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wiring


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash_pallas(q, k, v, causal, q_offset, window, block_q, block_k,
                  interpret, heads, kv_heads):
    out, _ = _fwd_pallas_call(
        q, k, v, causal, q_offset, window, block_q, block_k, interpret,
        heads=heads, kv_heads=kv_heads,
    )
    return out


def _flash_pallas_fwd(q, k, v, causal, q_offset, window, block_q, block_k,
                      interpret, heads, kv_heads):
    out, lse = _fwd_pallas_call(
        q, k, v, causal, q_offset, window, block_q, block_k, interpret,
        heads=heads, kv_heads=kv_heads,
    )
    return out, (q, k, v, out, lse)


def _flash_pallas_bwd(causal, q_offset, window, block_q, block_k, interpret,
                      heads, kv_heads, res, do):
    q, k, v, out, lse = res
    # delta = rowsum(dO ⊙ O): tiny elementwise reduce, XLA fuses it.
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    dq, dk, dv = _bwd_pallas_call(
        q, k, v, do, lse, delta, causal, q_offset, window,
        block_q, block_k, interpret, heads=heads, kv_heads=kv_heads,
    )
    return dq, dk, dv


_flash_pallas.defvjp(_flash_pallas_fwd, _flash_pallas_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _flash_pallas_masked(q, k, v, mask8, causal, q_offset, window,
                         block_q, block_k, interpret, heads, kv_heads):
    out, _ = _fwd_pallas_call(
        q, k, v, causal, q_offset, window, block_q, block_k, interpret,
        kv_mask8=mask8, heads=heads, kv_heads=kv_heads,
    )
    return out


def _flash_pallas_masked_fwd(q, k, v, mask8, causal, q_offset, window,
                             block_q, block_k, interpret, heads, kv_heads):
    out, lse = _fwd_pallas_call(
        q, k, v, causal, q_offset, window, block_q, block_k, interpret,
        kv_mask8=mask8, heads=heads, kv_heads=kv_heads,
    )
    return out, (q, k, v, mask8, out, lse)


def _flash_pallas_masked_bwd(causal, q_offset, window, block_q, block_k,
                             interpret, heads, kv_heads, res, do):
    import numpy as np

    q, k, v, mask8, out, lse = res
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    dq, dk, dv = _bwd_pallas_call(
        q, k, v, do, lse, delta, causal, q_offset, window,
        block_q, block_k, interpret, kv_mask8=mask8, heads=heads,
        kv_heads=kv_heads,
    )
    # Integer operands take float0 cotangents (masks have no tangent space).
    dmask = np.zeros(mask8.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dmask


_flash_pallas_masked.defvjp(_flash_pallas_masked_fwd, _flash_pallas_masked_bwd)


def _flash_attention_pallas(
    q, k, v, causal: bool, q_offset: int, window: int = 0,
    interpret: bool = False, kv_mask=None,
) -> jax.Array:
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    sk = k.shape[2]
    block_q = _pick_block(sq)
    block_k = _pick_block(sk)
    if not block_q or not block_k:
        raise ValueError(
            f"pallas flash attention needs 128-aligned sequence lengths, "
            f"got sq={sq}, sk={sk}; use impl='auto'/'xla'"
        )
    qf = q.reshape(b * h, sq, d)
    # GQA-native: K/V stay at their REAL head count; the kernels' index
    # maps fold the q-head → kv-head group mapping.
    kf = k.reshape(b * hkv, sk, d)
    vf = v.reshape(b * hkv, sk, d)
    if kv_mask is not None:
        mask8 = kv_mask.astype(jnp.int8).reshape(b, 1, sk)
        out = _flash_pallas_masked(
            qf, kf, vf, mask8, causal, q_offset, window, block_q, block_k,
            interpret, h, hkv,
        )
    else:
        out = _flash_pallas(
            qf, kf, vf, causal, q_offset, window, block_q, block_k,
            interpret, h, hkv,
        )
    return out.reshape(b, h, sq, d)
