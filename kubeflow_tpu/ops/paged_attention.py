"""Pallas paged-attention decode kernel: attend THROUGH the block tables.

The paged serving engine's decode step previously materialized each
slot's logical cache view before attending (``models/paged.py
_gathered_view``: ``pool[tables]`` → (B, Hkv, MAXB·BS, D) per layer per
step). Decode attention is cache-bandwidth-bound, so that gather roughly
triples the bytes crossing HBM per step: read the pool blocks, write the
contiguous copy, read it again inside attention — and it reads ALL MAXB
table slots, allocated or not.

This kernel reads each slot's blocks directly from the pool in HBM
(vLLM-style): one grid program per slot, double-buffered async DMA of
that slot's next (Hkv, BS, D) K and V blocks into VMEM while the current
block's scores accumulate into an online softmax. Bytes per step become
exactly one read of the slot's LIVE blocks — no materialized copy, no
dead-slot traffic — and the loop bound is the slot's own block count,
not MAXB.

Design notes:
- The block table and sequence lengths ride scalar prefetch
  (``pltpu.PrefetchScalarGridSpec``): physical block ids must be known
  to issue the DMA for a block, which is exactly what scalar-prefetch
  args exist for (pallas_guide: "enabling index computation for DMA").
- GQA runs on the unrepeated cache, like the dense-path
  ``_gqa_decode_attention``: q is viewed (Hkv, G, D) and each kv head's
  G query rows attend its single (BS, D) block — a (G, D)·(BS, D)ᵀ dot
  per head. FLOPs are trivial at decode; the kernel exists for the
  bytes, not the MXU.
- The kv_mask (holes + partial tail blocks) is applied per block from a
  VMEM-resident int8 mask, so semantics match the gathered path
  bit-for-bit (tests assert numerical agreement).
- bf16 pools only; int8-quantized pools and sliding-window configs keep
  the gathered path (models/paged.py dispatches).

Reference parity: the reference has no serving stack at all (SURVEY.md
§2.5); within this framework the kernel is the paged analogue of
ops/attention.py's flash kernel.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:  # pragma: no cover - exercised via the public entry point
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pallas unavailable: caller must use the gathered path
    pl = None
    pltpu = None


def _kernel(tables_ref, lens_ref, q_ref, kpool_ref, vpool_ref, mask_ref,
            o_ref, kbuf, vbuf, sems, *, block_size, n_kv_heads, group,
            head_dim):
    """Paged variant: block i of slot b lives at pool[tables[b, i]]."""
    b = pl.program_id(0)

    def kdma(slot, i):
        return pltpu.make_async_copy(
            kpool_ref.at[tables_ref[b, i]], kbuf.at[slot], sems.at[slot, 0]
        )

    def vdma(slot, i):
        return pltpu.make_async_copy(
            vpool_ref.at[tables_ref[b, i]], vbuf.at[slot], sems.at[slot, 1]
        )

    _attend(lens_ref[b], q_ref, mask_ref, o_ref, kbuf, vbuf, kdma, vdma,
            block_size=block_size, n_kv_heads=n_kv_heads, group=group,
            head_dim=head_dim)


def _dense_kernel(lens_ref, q_ref, kcache_ref, vcache_ref, mask_ref,
                  o_ref, kbuf, vbuf, sems, *, block_size, n_kv_heads,
                  group, head_dim):
    """Dense variant: block i of slot b is the contiguous slice
    cache[b, :, i·BS:(i+1)·BS, :] — a strided DMA instead of a table
    lookup; everything else (online softmax, masking) is shared."""
    b = pl.program_id(0)

    def kdma(slot, i):
        return pltpu.make_async_copy(
            kcache_ref.at[b, :, pl.ds(i * block_size, block_size), :],
            kbuf.at[slot], sems.at[slot, 0],
        )

    def vdma(slot, i):
        return pltpu.make_async_copy(
            vcache_ref.at[b, :, pl.ds(i * block_size, block_size), :],
            vbuf.at[slot], sems.at[slot, 1],
        )

    _attend(lens_ref[b], q_ref, mask_ref, o_ref, kbuf, vbuf, kdma, vdma,
            block_size=block_size, n_kv_heads=n_kv_heads, group=group,
            head_dim=head_dim)


def _attend(seq_len, q_ref, mask_ref, o_ref, kbuf, vbuf, kdma, vdma, *,
            block_size, n_kv_heads, group, head_dim):
    """Shared online-softmax block loop: double-buffered DMA via the
    caller-supplied kdma/vdma (paged table lookup or dense strided
    slice), accumulation per kv head on the unrepeated cache."""
    nblk = jnp.maximum((seq_len + block_size - 1) // block_size, 1)
    scale = 1.0 / math.sqrt(head_dim)

    q = q_ref[0].reshape(n_kv_heads, group, head_dim).astype(jnp.float32)

    # Warm up: first block's K and V in flight before the loop.
    kdma(0, 0).start()
    vdma(0, 0).start()

    m0 = jnp.full((n_kv_heads, group, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((n_kv_heads, group, 1), jnp.float32)
    acc0 = jnp.zeros((n_kv_heads, group, head_dim), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)
        nxt = 1 - slot

        @pl.when(i + 1 < nblk)
        def _():
            kdma(nxt, i + 1).start()
            vdma(nxt, i + 1).start()

        kdma(slot, i).wait()
        vdma(slot, i).wait()
        k = kbuf[slot].astype(jnp.float32)  # (Hkv, BS, D)
        v = vbuf[slot].astype(jnp.float32)

        # Validity = stored kv_mask AND the positional causal bound: the
        # batcher may mark a whole row True and lean on `k_pos <= pos`
        # (llama._gqa_decode_attention's mask), so both must apply here.
        k_pos = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (block_size,), 0
        )
        valid = (mask_ref[0, pl.ds(i * block_size, block_size)] != 0) & (
            k_pos < seq_len
        )  # (BS,)

        # Per-kv-head scores: (G, D) · (BS, D)ᵀ — static unroll over the
        # (small) kv-head count keeps every dot a plain 2D dot_general.
        dn = (((1,), (1,)), ((), ()))
        s = jnp.stack([
            jax.lax.dot_general(q[h], k[h], dn,
                                preferred_element_type=jnp.float32)
            for h in range(n_kv_heads)
        ]) * scale  # (Hkv, G, BS)
        s = jnp.where(valid[None, None, :], s, -jnp.inf)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # A fully-masked block (hole spanning a whole block) keeps
        # m_new = -inf; exp(-inf - -inf) would be NaN — pin alpha/p to 0.
        alpha = jnp.where(jnp.isfinite(m_new), jnp.exp(m - m_new), 0.0)
        p = jnp.where(jnp.isfinite(m_new), jnp.exp(s - m_new), 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.stack([
            jax.lax.dot_general(
                p[h], v[h], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for h in range(n_kv_heads)
        ])  # (Hkv, G, D)
        return m_new, l_new, acc * alpha + pv

    m, l, acc = jax.lax.fori_loop(0, nblk, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    o_ref[0] = out.reshape(n_kv_heads * group, head_dim).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_size", "interpret")
)
def paged_decode_attention(
    q: jax.Array,        # (B, Hq, D) — the single new token per slot
    k_pool: jax.Array,   # (NB, Hkv, BS, D) bf16 block pool
    v_pool: jax.Array,   # (NB, Hkv, BS, D)
    tables: jax.Array,   # (B, MAXB) int32 physical block ids
    kv_mask: jax.Array,  # (B, MAXB·BS) bool valid-key mask
    seq_lens: jax.Array,  # (B,) int32 — position+1 (bounds the block loop)
    block_size: int,
    interpret: bool = False,
) -> jax.Array:
    """Paged GQA decode attention; returns (B, Hq, D).

    Numerically equivalent to gathering the logical view and running
    ``models.llama._gqa_decode_attention`` with the same kv_mask
    (tests/test_paged_attention.py pins the agreement); reads only the
    ``ceil(seq_len/BS)`` live blocks per slot.
    """
    if pl is None:
        raise RuntimeError("pallas unavailable; use the gathered path")
    b, hq, d = q.shape
    nb, hkv, bs, _ = k_pool.shape
    if bs != block_size:
        raise ValueError(f"pool block size {bs} != block_size {block_size}")
    if hq % hkv:
        raise ValueError(f"{hq} q heads not divisible by {hkv} kv heads")
    max_blocks = tables.shape[1]
    if kv_mask.shape != (b, max_blocks * bs):
        # The mask BlockSpec reads exactly (1, MAXB·BS) per slot — a mask
        # built for a different table layout would be silently truncated
        # or misaligned into wrong attention, not a shape error.
        raise ValueError(
            f"kv_mask shape {kv_mask.shape} != ({b}, {max_blocks * bs}) "
            "(tables × block_size layout)"
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hq, d), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, max_blocks * bs), lambda i, *_: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, hq, d), lambda i, *_: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, hkv, bs, d), k_pool.dtype),
            pltpu.VMEM((2, hkv, bs, d), v_pool.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(
        _kernel, block_size=block_size, n_kv_heads=hkv, group=hq // hkv,
        head_dim=d,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, d), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), seq_lens.astype(jnp.int32),
      q, k_pool, v_pool, kv_mask.astype(jnp.int8))


@functools.partial(
    jax.jit, static_argnames=("block_size", "interpret")
)
def dense_decode_attention(
    q: jax.Array,        # (B, Hq, D) — the single new token per slot
    k_cache: jax.Array,  # (B, Hkv, C, D) bf16 per-slot dense cache
    v_cache: jax.Array,  # (B, Hkv, C, D)
    kv_mask: jax.Array,  # (B, C) valid-key mask
    seq_lens: jax.Array,  # (B,) int32 — position+1 (bounds the read)
    block_size: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Length-bounded dense GQA decode attention; returns (B, Hq, D).

    The dense serving cache's XLA decode reads ALL C cache slots per
    step per slot — a server with cache_len 4096 and a slot 200 tokens
    in pays 20× its useful cache traffic. This variant shares the paged
    kernel's online-softmax block loop, but "block i" is the contiguous
    slice cache[b, :, i·BS:(i+1)·BS, :] (strided DMA, no table), so each
    slot reads only ``ceil(seq_len/BS)`` chunks. C must divide by
    block_size; masking matches ``_gqa_decode_attention`` exactly
    (stored mask AND the positional causal bound).
    """
    if pl is None:
        raise RuntimeError("pallas unavailable; use the XLA path")
    b, hq, d = q.shape
    _, hkv, c, _ = k_cache.shape
    if c % block_size:
        raise ValueError(
            f"cache_len {c} not divisible by block_size {block_size}"
        )
    if hq % hkv:
        raise ValueError(f"{hq} q heads not divisible by {hkv} kv heads")
    if kv_mask.shape != (b, c):
        raise ValueError(f"kv_mask shape {kv_mask.shape} != ({b}, {c})")

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hq, d), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, c), lambda i, *_: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, hq, d), lambda i, *_: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, hkv, block_size, d), k_cache.dtype),
            pltpu.VMEM((2, hkv, block_size, d), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(
        _dense_kernel, block_size=block_size, n_kv_heads=hkv,
        group=hq // hkv, head_dim=d,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, d), q.dtype),
        interpret=interpret,
    )(seq_lens.astype(jnp.int32), q, k_cache, v_cache,
      kv_mask.astype(jnp.int8))
