"""Pallas ragged paged attention: one fused dispatch for mixed batches.

The paged serving engine alternated two compiled shapes per scheduling
round: a (1, Lb) prompt prefill (``_paged_admit``) and a (B, 1) decode
step — every admission stalled every in-flight decode for a full
prefill, and short prompt chunks left the MXU idle. Ragged paged
attention (arXiv 2604.15464) fuses both into ONE dispatch over a
flattened token axis: N decode tokens plus M variable-length prefill
chunks become a single ``(total_tokens,)`` batch with per-SEQUENCE
``(seq_start, seq_len, kv_len)`` metadata describing which contiguous
row span belongs to which slot and where that slot's KV history ends.

This module owns the attention math for that layout:

- ``ragged_paged_attention`` — the Pallas TPU kernel. One grid program
  per sequence; each program tiles its query span ``q_tile`` rows at a
  time (DMA'd from the flattened q in HBM into VMEM) and streams the
  slot's KV blocks through the same double-buffered async-copy online
  softmax as ops/paged_attention.py. Causality INSIDE the ragged chunk
  falls out of absolute positions: query j of a chunk whose last token
  sits at kv position ``kv_len - 1`` lives at ``kv_len - seq_len + j``
  and attends ``k_pos <= kv_len - seq_len + j`` — so a decode token
  (seq_len 1) sees its whole history and a prefill chunk is triangular
  over itself, with no separate mask plumbing. Per-sequence KV blocks
  are read ONCE and amortized over the whole chunk, instead of once per
  token as a (T, 1)-shaped decode dispatch would.
- ``ragged_attention_reference`` — the pure-jnp gather/segment-softmax
  fallback, selected off-TPU (tier-1 runs CPU): derives each row's
  owning sequence from the metadata, gathers the slot's logical view
  through the tables, and applies the identical validity rule
  (stored kv_mask AND ``k_pos <= q_pos``) in f32 — token-exact vs the
  dense ``_gqa_decode_attention`` path by construction.

Layout contract (enforced by the wrapper, produced by the schedulers):
- sequences occupy disjoint, LEFT-TO-RIGHT row spans of q: seq_starts
  is non-decreasing and span i ends before span i+1 begins. The kernel
  relies on this — a partial last q-tile's spill rows land on the NEXT
  sequence's span, which a LATER grid program overwrites (TPU grid
  iterations run sequentially).
- ``seq_lens[s] == 0`` marks an inactive slot (its program is a no-op).
- kv_mask carries PADDING validity only; future positions may stay True
  because the positional bound already hides them (the same convention
  models/paged.py documents for its decode step).
- Speculative verify spans are ordinary clients of this contract: a
  decoding slot in spec mode contributes a (1 + draft_len) row span —
  last committed token plus the draft proposals — and the positional
  bound ``k_pos <= kv_len - seq_len + j`` makes each verify row causal
  over exactly the prefix it would see in sequential decode, so target
  verification of all draft positions rides the same fused dispatch as
  plain decode rows and prefill chunks with no kernel changes
  (models/speculative.py ``_spec_step_ragged`` builds these spans).

Pools are bf16 OR int8-value + bf16-scale (the quantize-on-write format
``models/paged.py`` produces for ``kv_bits=8``): pass ``k_scale_pool``/
``v_scale_pool`` of shape (NB, Hkv, BS) and both paths dequantize each
block as ``value.astype(f32) * scale[..., None]`` — one extra (Hkv, BS)
DMA per block in the kernel, amortized over the whole chunk exactly
like the values. Sliding-window configs keep the gathered path
(models/paged.py dispatches, same contract as the decode kernel).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:  # pragma: no cover - exercised via the public entry point
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pallas unavailable: caller must use the reference
    pl = None
    pltpu = None


def _ragged_kernel(starts_ref, lens_ref, kvlens_ref, tables_ref,
                   q_hbm, kpool_ref, vpool_ref, *rest,
                   block_size, q_tile, n_kv_heads, group, head_dim,
                   quantized):
    """One program per sequence: tile the query span, stream KV blocks.

    ``quantized`` is static: it decides at trace time whether the pool
    carries int8 values with bf16 scale planes (two extra refs + two
    extra scratch buffers in ``rest``) or plain bf16 values.
    """
    if quantized:
        (kspool_ref, vspool_ref, mask_ref, o_hbm, qbuf, obuf,
         kbuf, vbuf, ksbuf, vsbuf, sems, qsem, osem) = rest
    else:
        kspool_ref = vspool_ref = ksbuf = vsbuf = None
        (mask_ref, o_hbm, qbuf, obuf, kbuf, vbuf,
         sems, qsem, osem) = rest
    s = pl.program_id(0)
    qlen = lens_ref[s]

    @pl.when(qlen > 0)
    def _():
        start = starts_ref[s]
        kvlen = kvlens_ref[s]
        base = kvlen - qlen  # absolute kv position of the chunk's row 0
        scale = 1.0 / math.sqrt(head_dim)
        nqt = (qlen + q_tile - 1) // q_tile

        def kdma(slot, i):
            return pltpu.make_async_copy(
                kpool_ref.at[tables_ref[s, i]], kbuf.at[slot],
                sems.at[slot, 0],
            )

        def vdma(slot, i):
            return pltpu.make_async_copy(
                vpool_ref.at[tables_ref[s, i]], vbuf.at[slot],
                sems.at[slot, 1],
            )

        def ksdma(slot, i):
            return pltpu.make_async_copy(
                kspool_ref.at[tables_ref[s, i]], ksbuf.at[slot],
                sems.at[slot, 2],
            )

        def vsdma(slot, i):
            return pltpu.make_async_copy(
                vspool_ref.at[tables_ref[s, i]], vsbuf.at[slot],
                sems.at[slot, 3],
            )

        def tile_body(t, _):
            row0 = start + t * q_tile
            qcopy = pltpu.make_async_copy(
                q_hbm.at[pl.ds(row0, q_tile)], qbuf, qsem
            )
            qcopy.start()
            qcopy.wait()
            # Rows are (token, group) pairs: row j // group is query
            # token j // G of this tile, at absolute position
            # base + t·q_tile + j // G. The tile's KV bound is its LAST
            # query's position + 1 (clamped to the stored length).
            q = jnp.stack([
                qbuf[:, h * group:(h + 1) * group, :]
                .reshape(q_tile * group, head_dim).astype(jnp.float32)
                for h in range(n_kv_heads)
            ])  # (Hkv, q_tile·G, D)
            q_pos = (base + t * q_tile) + jax.lax.broadcasted_iota(
                jnp.int32, (q_tile * group, 1), 0
            ) // group  # (q_tile·G, 1)
            hi = jnp.minimum(base + (t + 1) * q_tile, kvlen)
            nblk = jnp.maximum((hi + block_size - 1) // block_size, 1)

            kdma(0, 0).start()
            vdma(0, 0).start()
            if quantized:
                ksdma(0, 0).start()
                vsdma(0, 0).start()
            m0 = jnp.full((n_kv_heads, q_tile * group, 1), -jnp.inf,
                          jnp.float32)
            l0 = jnp.zeros((n_kv_heads, q_tile * group, 1), jnp.float32)
            acc0 = jnp.zeros((n_kv_heads, q_tile * group, head_dim),
                             jnp.float32)

            def body(i, carry):
                m, l, acc = carry
                slot = jax.lax.rem(i, 2)
                nxt = 1 - slot

                @pl.when(i + 1 < nblk)
                def _():
                    kdma(nxt, i + 1).start()
                    vdma(nxt, i + 1).start()
                    if quantized:
                        ksdma(nxt, i + 1).start()
                        vsdma(nxt, i + 1).start()

                kdma(slot, i).wait()
                vdma(slot, i).wait()
                k = kbuf[slot].astype(jnp.float32)  # (Hkv, BS, D)
                v = vbuf[slot].astype(jnp.float32)
                if quantized:
                    ksdma(slot, i).wait()
                    vsdma(slot, i).wait()
                    k = k * ksbuf[slot].astype(jnp.float32)[..., None]
                    v = v * vsbuf[slot].astype(jnp.float32)[..., None]

                # Validity = stored kv_mask AND the positional causal
                # bound per (query row, key) pair — identical rule to
                # the decode kernel, widened to a 2D tile.
                k_pos = i * block_size + jax.lax.broadcasted_iota(
                    jnp.int32, (1, block_size), 1
                )  # (1, BS)
                valid = (
                    mask_ref[0, pl.ds(i * block_size, block_size)][None, :]
                    != 0
                ) & (k_pos <= q_pos)  # (q_tile·G, BS)

                dn = (((1,), (1,)), ((), ()))
                sc = jnp.stack([
                    jax.lax.dot_general(q[h], k[h], dn,
                                        preferred_element_type=jnp.float32)
                    for h in range(n_kv_heads)
                ]) * scale  # (Hkv, q_tile·G, BS)
                sc = jnp.where(valid[None], sc, -jnp.inf)

                m_new = jnp.maximum(m, jnp.max(sc, axis=-1, keepdims=True))
                # Fully-masked rows (pad blocks, garbage tail rows) keep
                # m_new = -inf; exp(-inf - -inf) would be NaN — pin to 0.
                alpha = jnp.where(jnp.isfinite(m_new),
                                  jnp.exp(m - m_new), 0.0)
                p = jnp.where(jnp.isfinite(m_new), jnp.exp(sc - m_new), 0.0)
                l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
                pv = jnp.stack([
                    jax.lax.dot_general(
                        p[h], v[h], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    for h in range(n_kv_heads)
                ])  # (Hkv, q_tile·G, D)
                return m_new, l_new, acc * alpha + pv

            m, l, acc = jax.lax.fori_loop(0, nblk, body, (m0, l0, acc0))
            out = acc / jnp.maximum(l, 1e-30)
            for h in range(n_kv_heads):
                obuf[:, h * group:(h + 1) * group, :] = (
                    out[h].reshape(q_tile, group, head_dim)
                    .astype(obuf.dtype)
                )
            ocopy = pltpu.make_async_copy(
                obuf, o_hbm.at[pl.ds(row0, q_tile)], osem
            )
            ocopy.start()
            ocopy.wait()

        jax.lax.fori_loop(0, nqt, tile_body, None)


@functools.partial(
    jax.jit, static_argnames=("block_size", "q_tile", "interpret")
)
def ragged_paged_attention(
    q: jax.Array,          # (T, Hq, D) flattened mixed-batch queries
    k_pool: jax.Array,     # (NB, Hkv, BS, D) bf16 block pool
    v_pool: jax.Array,     # (NB, Hkv, BS, D)
    tables: jax.Array,     # (S, MAXB) int32 physical block ids per slot
    kv_mask: jax.Array,    # (S, MAXB·BS) bool valid-key mask per slot
    seq_starts: jax.Array,  # (S,) int32 — first q row of each sequence
    seq_lens: jax.Array,    # (S,) int32 — q rows this step (0 = inactive)
    kv_lens: jax.Array,     # (S,) int32 — kv length INCLUDING this chunk
    block_size: int,
    q_tile: int = 16,
    interpret: bool = False,
    k_scale_pool: jax.Array | None = None,  # (NB, Hkv, BS) bf16 scales
    v_scale_pool: jax.Array | None = None,  # (NB, Hkv, BS)
) -> jax.Array:
    """Ragged paged GQA attention over a mixed batch; returns (T, Hq, D).

    Row r of sequence s (``seq_starts[s] <= r < seq_starts[s] +
    seq_lens[s]``) attends slot s's pool blocks at kv positions
    ``<= kv_lens[s] - seq_lens[s] + (r - seq_starts[s])`` where kv_mask
    allows — numerically the gathered ``_gqa_decode_attention`` rule
    (``ragged_attention_reference`` pins the agreement). Rows belonging
    to no sequence return unspecified values; callers never read them.

    With ``k_scale_pool``/``v_scale_pool`` the value pools are int8 and
    each streamed block is dequantized in-register before the softmax —
    the ``kv_bits=8`` pool format.
    """
    if pl is None:
        raise RuntimeError("pallas unavailable; use the reference path")
    t, hq, d = q.shape
    nb, hkv, bs, _ = k_pool.shape
    if bs != block_size:
        raise ValueError(f"pool block size {bs} != block_size {block_size}")
    if hq % hkv:
        raise ValueError(f"{hq} q heads not divisible by {hkv} kv heads")
    quantized = k_scale_pool is not None
    if quantized != (v_scale_pool is not None):
        raise ValueError("k_scale_pool and v_scale_pool must come together")
    if quantized and k_scale_pool.shape != (nb, hkv, bs):
        raise ValueError(
            f"scale pool shape {k_scale_pool.shape} != {(nb, hkv, bs)} "
            "(one scale per stored kv position)"
        )
    s, max_blocks = tables.shape
    if kv_mask.shape != (s, max_blocks * bs):
        raise ValueError(
            f"kv_mask shape {kv_mask.shape} != ({s}, {max_blocks * bs}) "
            "(tables × block_size layout)"
        )
    # One q_tile of slack absorbs the last active tile's spill rows (the
    # kernel writes whole tiles; see the layout contract in the module
    # docstring) and keeps every tile's q DMA in bounds.
    qp = jnp.pad(q, ((0, q_tile), (0, 0), (0, 0)))

    scale_specs = (
        [pl.BlockSpec(memory_space=pl.ANY),
         pl.BlockSpec(memory_space=pl.ANY)] if quantized else []
    )
    scale_scratch = (
        [pltpu.VMEM((2, hkv, bs), k_scale_pool.dtype),
         pltpu.VMEM((2, hkv, bs), v_scale_pool.dtype)] if quantized else []
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # q: tiles DMA'd per seq
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            *scale_specs,
            pl.BlockSpec((1, max_blocks * bs), lambda i, *_: (i, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((q_tile, hq, d), q.dtype),
            pltpu.VMEM((q_tile, hq, d), q.dtype),
            pltpu.VMEM((2, hkv, bs, d), k_pool.dtype),
            pltpu.VMEM((2, hkv, bs, d), v_pool.dtype),
            *scale_scratch,
            pltpu.SemaphoreType.DMA((2, 4 if quantized else 2)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel, block_size=block_size, q_tile=q_tile,
        n_kv_heads=hkv, group=hq // hkv, head_dim=d, quantized=quantized,
    )
    scale_args = [k_scale_pool, v_scale_pool] if quantized else []
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t + q_tile, hq, d), q.dtype),
        interpret=interpret,
    )(seq_starts.astype(jnp.int32), seq_lens.astype(jnp.int32),
      kv_lens.astype(jnp.int32), tables.astype(jnp.int32),
      qp, k_pool, v_pool, *scale_args, kv_mask.astype(jnp.int8))
    return out[:t]


@functools.partial(jax.jit, static_argnames=("block_size",))
def ragged_attention_reference(
    q: jax.Array,          # (T, Hq, D)
    k_pool: jax.Array,     # (NB, Hkv, BS, D)
    v_pool: jax.Array,     # (NB, Hkv, BS, D)
    tables: jax.Array,     # (S, MAXB)
    kv_mask: jax.Array,    # (S, MAXB·BS)
    seq_starts: jax.Array,  # (S,)
    seq_lens: jax.Array,    # (S,)
    kv_lens: jax.Array,     # (S,)
    block_size: int,
    k_scale_pool: jax.Array | None = None,  # (NB, Hkv, BS)
    v_scale_pool: jax.Array | None = None,  # (NB, Hkv, BS)
) -> jax.Array:
    """Pure-jnp gather/segment-softmax fallback; returns (T, Hq, D).

    The off-TPU selection of the ragged path: gathers each row's slot
    view through the tables and applies the identical validity rule in
    f32. Rows owned by no sequence come out 0 (never read). Same
    numerics as the gathered ``_gqa_decode_attention`` — this is the
    function the parity suite holds both the kernel and the schedulers
    against. Scale pools dequantize int8 values exactly like the
    kernel: ``value.astype(f32) * scale[..., None]``.
    """
    t, hq, d = q.shape
    s, maxb = tables.shape
    hkv = k_pool.shape[1]
    group = hq // hkv
    if (k_scale_pool is None) != (v_scale_pool is None):
        raise ValueError("k_scale_pool and v_scale_pool must come together")
    rows = jnp.arange(t)
    in_seq = (rows[None, :] >= seq_starts[:, None]) & (
        rows[None, :] < (seq_starts + seq_lens)[:, None]
    )  # (S, T)
    tok_seq = jnp.argmax(in_seq, axis=0)  # (T,), 0 where unowned
    tok_own = jnp.any(in_seq, axis=0)
    tok_pos = (
        kv_lens[tok_seq] - seq_lens[tok_seq]
        + rows - seq_starts[tok_seq]
    )  # absolute kv position per row

    def gathered(pool, scale=None):
        g = pool[tables]  # (S, MAXB, Hkv, BS, D)
        g = g.transpose(0, 2, 1, 3, 4).reshape(
            s, hkv, maxb * block_size, d
        )
        if scale is None:
            return g
        sg = scale[tables].transpose(0, 2, 1, 3).reshape(
            s, hkv, maxb * block_size
        )  # (S, Hkv, L)
        return g.astype(jnp.float32) * sg.astype(jnp.float32)[..., None]

    kg = gathered(k_pool, k_scale_pool)[tok_seq].astype(jnp.float32)
    vg = gathered(v_pool, v_scale_pool)[tok_seq].astype(jnp.float32)
    qf = q.reshape(t, hkv, group, d).astype(jnp.float32)
    scores = jnp.einsum("thgd,thld->thgl", qf, kg) / math.sqrt(d)
    k_pos = jnp.arange(maxb * block_size)
    valid = (
        kv_mask[tok_seq][:, None, None, :]
        & (k_pos[None, None, None, :] <= tok_pos[:, None, None, None])
        & tok_own[:, None, None, None]
    )
    scores = jnp.where(valid, scores, -jnp.inf)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.where(jnp.isfinite(m), jnp.exp(scores - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("thgl,thld->thgd", p, vg) / jnp.maximum(l, 1e-30)
    return out.reshape(t, hq, d).astype(q.dtype)
