"""kubeflow-tpu: a TPU-native Kubernetes notebook platform.

A ground-up rebuild of the capabilities of the opendatahub-io/kubeflow
notebook subsystem (notebook-controller + odh-notebook-controller, see
reference components/notebook-controller and components/odh-notebook-controller)
with TPUs as a first-class concept:

- The ``Notebook`` CRD gains ``spec.tpu`` accelerator/topology fields
  (kubeflow_tpu.api).
- The core reconciler emits *indexed* StatefulSets with ``google.com/tpu``
  resources and ``cloud.google.com/gke-tpu-topology`` nodeSelectors — one pod
  per TPU host of the slice (kubeflow_tpu.controller).
- The mutating webhook injects ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` /
  libtpu environment instead of CUDA env (kubeflow_tpu.webhook).
- The idle culler tracks Jupyter activity across every host of a multi-host
  slice and releases the slice atomically on cull or preemption
  (kubeflow_tpu.controller.culling).
- In-notebook runtime helpers bring up ``jax.distributed`` over the slice and
  build device meshes (kubeflow_tpu.runtime), with a JAX/pallas model stack
  (kubeflow_tpu.models, kubeflow_tpu.ops, kubeflow_tpu.parallel) for
  benchmark parity (Llama-2-7B tokens/sec/chip).
"""

__version__ = "0.3.0"
