from kubeflow_tpu.metrics.metrics import Metrics  # noqa: F401
