"""/metrics HTTP endpoint.

Reference parity: controller-runtime serves the Prometheus registry on the
``--metrics-addr`` listener (reference components/notebook-controller/
main.go:80-94 metrics server options; ODH adds TLS opts main.go:239). Here
a small threaded server renders ``Metrics.expose()`` — which recomputes the
run-state gauges by listing StatefulSets on every scrape, exactly as the
reference's custom Collector does (pkg/metrics/metrics.go:82-99).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kubeflow_tpu.metrics.metrics import Metrics

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Serves one Metrics registry on the metrics address."""

    def __init__(self, metrics: Metrics, host: str = "127.0.0.1", port: int = 0):
        self.metrics = metrics
        registry = self.metrics

        class Handler(BaseHTTPRequestHandler):
            # Avoid Nagle+delayed-ACK ~40ms stalls per request.
            disable_nagle_algorithm = True
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.rstrip("/") not in ("/metrics", ""):
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                try:
                    payload = registry.expose()
                    code = 200
                except Exception as err:  # scrape must not kill the server
                    payload = f"# scrape error: {err}\n".encode()
                    code = 500
                self.send_response(code)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
