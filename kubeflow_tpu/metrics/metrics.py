"""Prometheus metrics: the reference metric set plus TPU slice metrics.

Reference set (components/notebook-controller/pkg/metrics/metrics.go:22-60):
``notebook_create_total``, ``notebook_create_failed_total``,
``notebook_culling_total``, ``last_notebook_culling_timestamp_seconds``, and
a ``notebook_running`` gauge computed by listing StatefulSets
(metrics.go:82-99, a custom Collector).

TPU-native additions (SURVEY.md §7 step 6): ``tpu_slice_ready_seconds`` (the
p50 spawn north-star), ``tpu_slice_hosts`` / ``tpu_chips_total`` capacity
gauges, and preemption/cull reclaim counters.
"""

from __future__ import annotations

from typing import Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from kubeflow_tpu.k8s.client import Client

# Every serving/engine metric family must stay visible in the servers' JSON
# /stats payload so an operator tailing /stats and a dashboard scraping
# /metrics can never disagree about which observables exist.  The value is
# the /stats key the family surfaces under (a string literal that must
# appear in models/server.py or models/gateway.py); kftpu-lint's
# metric-stats-parity rule enforces both directions of this table.
STATS_PARITY = {
    "tpu_serving_requests_shed_total": "requests_shed",
    "tpu_serving_requests_cancelled_total": "requests_cancelled",
    "tpu_serving_deadline_expired_total": "deadline_expired",
    "tpu_serving_queue_depth": "queued",
    "tpu_serving_drain_seconds": "drain_duration_s",
    "tpu_serving_ragged_batch_fill": "batch_fill",
    "tpu_serving_prefix_cache_hits_total": "hits",
    "tpu_serving_prefix_cache_misses_total": "misses",
    "tpu_serving_prefix_cache_evictions_total": "evictions",
    "tpu_serving_prefix_cached_blocks": "cached_blocks",
    "tpu_engine_step_stall_total": "engine_step_stalls",
    "tpu_gateway_requests_total": "requests",
    "tpu_gateway_reroutes_total": "reroutes",
    "tpu_gateway_shed_total": "shed",
    "tpu_gateway_replicas": "ring_size",
    "tpu_serving_kv_transfer_total": "kv_transfers",
    "tpu_serving_kv_transfer_failures_total": "kv_transfer_failures",
    "tpu_serving_kv_transfer_bytes_total": "kv_transfer_bytes",
    "tpu_serving_kv_transfer_latency_seconds": "kv_transfer_latency_s",
    "tpu_serving_kv_peer_fetch_total": "kv_peer_fetches",
    "tpu_serving_kv_peer_fetch_failures_total": "kv_peer_fetch_failures",
    "tpu_serving_kv_peer_bytes_total": "kv_peer_bytes",
    "tpu_serving_kv_peer_fetch_latency_seconds": "kv_peer_fetch_latency_s",
    "tpu_serving_kv_swap_out_total": "swap_out",
    "tpu_serving_kv_swap_in_total": "swap_in",
    "tpu_serving_kv_swap_restored_tokens_total": "restored_tokens",
    "tpu_serving_kv_swap_bytes": "swap_bytes",
    "tpu_serving_spec_accept_total": "accepted",
    "tpu_serving_spec_rounds_total": "rounds",
    "tpu_serving_lora_cache_hits_total": "hits",
    "tpu_serving_lora_cache_misses_total": "misses",
    "tpu_serving_lora_cache_evictions_total": "evictions",
    "tpu_autoscaler_scale_up_total": "scale_ups",
    "tpu_autoscaler_scale_down_total": "scale_downs",
    "tpu_autoscaler_hold_total": "holds",
    "tpu_autoscaler_freeze_total": "freezes",
    "tpu_autoscaler_claim_attempts_total": "claim_attempts",
    "tpu_autoscaler_claim_failures_total": "claim_failures",
    "tpu_autoscaler_claim_latency_seconds": "claim_latency_s",
    "tpu_autoscaler_replicas": "tier_replicas",
    "tpu_migration_started_total": "migrations_started",
    "tpu_migration_completed_total": "migrations_completed",
    "tpu_migration_fallback_total": "migrations_fell_back",
    "tpu_migration_seconds": "migration_last_s",
}


class Metrics:
    """Per-manager metric bundle with an isolated registry (testable)."""

    def __init__(self, client: Optional[Client] = None):
        self.registry = CollectorRegistry()
        self.client = client
        self.create_total = Counter(
            "notebook_create_total",
            "Total times the controller created a notebook StatefulSet",
            registry=self.registry,
        )
        self.create_failed_total = Counter(
            "notebook_create_failed_total",
            "Total notebook StatefulSet creation failures",
            registry=self.registry,
        )
        self.culling_total = Counter(
            "notebook_culling_total",
            "Total notebooks culled for idleness",
            registry=self.registry,
        )
        self.last_culling_timestamp = Gauge(
            "last_notebook_culling_timestamp_seconds",
            "Unix time of the most recent culling",
            registry=self.registry,
        )
        # -- TPU-native additions ------------------------------------------
        self.slice_ready_seconds = Histogram(
            "tpu_slice_ready_seconds",
            "Seconds from Notebook creation to all slice hosts Ready",
            buckets=(5, 10, 20, 30, 45, 60, 90, 120, 180, 300, 600),
            registry=self.registry,
        )
        self.slice_preemptions_total = Counter(
            "tpu_slice_preemptions_total",
            "Slice host preemptions/evictions observed",
            registry=self.registry,
        )
        self.slice_recovery_seconds = Histogram(
            "tpu_slice_recovery_seconds",
            "Seconds from slice interruption to all hosts Ready again",
            buckets=(10, 30, 60, 120, 300, 600, 1200, 1800, 3600),
            registry=self.registry,
        )
        self.slice_recovery_escalations_total = Counter(
            "tpu_slice_recovery_escalations_total",
            "Recovery escalations (warm-pool claim or StatefulSet recreate)",
            registry=self.registry,
        )
        self.slice_recovery_failed_total = Counter(
            "tpu_slice_recovery_failed_total",
            "Interruptions that exhausted escalations and went terminal",
            registry=self.registry,
        )
        self.chips_reclaimed_total = Counter(
            "tpu_chips_reclaimed_total",
            "TPU chips released by culling or stop",
            registry=self.registry,
        )
        self.pool_claims_total = Counter(
            "tpu_slicepool_claims_total",
            "Warm slices claimed by notebook spawns",
            registry=self.registry,
        )
        self.pool_claim_misses_total = Counter(
            "tpu_slicepool_claim_misses_total",
            "TPU notebook spawns that found no matching warm slice",
            registry=self.registry,
        )
        self.pool_warm_ready = Gauge(
            "tpu_slicepool_warm_ready",
            "All-Ready warm placeholder slices per pool",
            ["pool"],
            registry=self.registry,
        )
        self.running = Gauge(
            "notebook_running",
            "Currently running notebooks (replicas > 0)",
            registry=self.registry,
        )
        self.tpu_chips_in_use = Gauge(
            "tpu_chips_in_use",
            "TPU chips currently held by running notebook slices",
            registry=self.registry,
        )
        self.prepull_nodes_covered = Gauge(
            "tpu_prepull_nodes_covered",
            "TPU nodes whose pre-pull pod Succeeded for the current image set",
            registry=self.registry,
        )
        self.prepull_nodes_target = Gauge(
            "tpu_prepull_nodes_target",
            "TPU nodes the image pre-puller is maintaining pods for",
            registry=self.registry,
        )
        # -- checkpoint durability (runtime/checkpoint.py) -----------------
        # Exposed from the notebook runtime when the manager is built with
        # metrics=; save duration feeds the emergency-save budget heuristic
        # (a save slower than the grace window is skipped, not torn).
        self.checkpoint_save_seconds = Histogram(
            "tpu_checkpoint_save_seconds",
            "Wall-clock duration of committed checkpoint saves",
            buckets=(0.1, 0.5, 1, 2, 5, 10, 20, 30, 60, 120, 300),
            registry=self.registry,
        )
        self.checkpoint_corrupt_total = Counter(
            "tpu_checkpoint_corrupt_total",
            "Checkpoint steps that failed manifest validation and were "
            "quarantined at restore",
            registry=self.registry,
        )
        self.checkpoint_emergency_total = Counter(
            "tpu_checkpoint_emergency_total",
            "Emergency (SIGTERM grace-window) checkpoint saves committed",
            registry=self.registry,
        )
        # -- serving request lifecycle (models/server.py) ------------------
        # The InferenceServer mirrors its /stats lifecycle counters here
        # when constructed with metrics=; shed/cancel/deadline rates are
        # the overload-protection observables the chaos experiments pin.
        self.serving_requests_shed_total = Counter(
            "tpu_serving_requests_shed_total",
            "Requests refused with 429 because the pending queue was full",
            registry=self.registry,
        )
        self.serving_requests_cancelled_total = Counter(
            "tpu_serving_requests_cancelled_total",
            "Requests cancelled before completing (client disconnects)",
            registry=self.registry,
        )
        self.serving_deadline_expired_total = Counter(
            "tpu_serving_deadline_expired_total",
            "Requests retired engine-side after their deadline expired",
            registry=self.registry,
        )
        self.serving_queue_depth = Gauge(
            "tpu_serving_queue_depth",
            "Pending (unslotted) inference requests",
            registry=self.registry,
        )
        self.serving_drain_seconds = Gauge(
            "tpu_serving_drain_seconds",
            "Duration of the most recent graceful drain",
            registry=self.registry,
        )
        self.serving_ragged_batch_fill = Gauge(
            "tpu_serving_ragged_batch_fill",
            "Fraction of the ragged engine's last-step token budget "
            "carrying real (decode or prefill-chunk) tokens",
            registry=self.registry,
        )
        # -- engine flight recorder (observability/flight.py) --------------
        # Mirrored from the recorder's stall ledger by the InferenceServer
        # drive loop (same delta pattern as the prefix-cache counters).
        self.engine_step_stall_total = Counter(
            "tpu_engine_step_stall_total",
            "Engine steps whose duration exceeded the flight recorder's "
            "stall threshold (k x rolling-median step time)",
            registry=self.registry,
        )
        # -- prefix cache (models/paged.py PagedBatcher(prefix_cache=True))
        # Mirrored from the engine's host-side counters by the
        # InferenceServer drive loop; the gateway scrapes the same numbers
        # from /stats for its routing report, so the fleet-level hit ratio
        # and the per-replica Prometheus view can never disagree.
        self.serving_prefix_cache_hits_total = Counter(
            "tpu_serving_prefix_cache_hits_total",
            "Prompt blocks admitted from the warm prefix-chain cache "
            "(prefill skipped for these blocks)",
            registry=self.registry,
        )
        self.serving_prefix_cache_misses_total = Counter(
            "tpu_serving_prefix_cache_misses_total",
            "Registrable prompt blocks that missed the prefix-chain cache "
            "and were prefetched cold",
            registry=self.registry,
        )
        self.serving_prefix_cache_evictions_total = Counter(
            "tpu_serving_prefix_cache_evictions_total",
            "Prefix-chain leaf blocks evicted to make room in the block "
            "pool",
            registry=self.registry,
        )
        self.serving_prefix_cached_blocks = Gauge(
            "tpu_serving_prefix_cached_blocks",
            "Blocks currently registered on warm prefix chains",
            registry=self.registry,
        )
        # -- fleet gateway (models/gateway.py ServingGateway) --------------
        self.gateway_requests_total = Counter(
            "tpu_gateway_requests_total",
            "Completion requests accepted and proxied to a replica",
            registry=self.registry,
        )
        self.gateway_reroutes_total = Counter(
            "tpu_gateway_reroutes_total",
            "Requests re-routed to the next ring node after a "
            "503/429/connect failure (bounded by the re-route budget)",
            registry=self.registry,
        )
        # The tenant label is bounded by the gateway's top-K + "other"
        # bucketing (signals.TenantBuckets), never raw tenant names.
        self.gateway_shed_total = Counter(
            "tpu_gateway_shed_total",
            "Requests shed by the gateway's tenant-fair admission when "
            "the whole fleet reported overload",
            ["tenant"],
            registry=self.registry,
        )
        self.gateway_replicas = Gauge(
            "tpu_gateway_replicas",
            "Replicas currently routable (present in the hash ring)",
            registry=self.registry,
        )
        # -- disaggregated serving (prefill→decode paged-KV handoff) ------
        self.serving_kv_transfer_total = Counter(
            "tpu_serving_kv_transfer_total",
            "Prefill→decode KV handoffs completed by the gateway",
            registry=self.registry,
        )
        self.serving_kv_transfer_failures_total = Counter(
            "tpu_serving_kv_transfer_failures_total",
            "KV handoffs that failed (prefill hop, transfer, or decode "
            "import) and fell back within the re-route budget",
            registry=self.registry,
        )
        self.serving_kv_transfer_bytes_total = Counter(
            "tpu_serving_kv_transfer_bytes_total",
            "Serialized KV payload bytes shipped prefill→decode",
            registry=self.registry,
        )
        self.serving_kv_transfer_latency_seconds = Gauge(
            "tpu_serving_kv_transfer_latency_seconds",
            "Duration of the most recent KV transfer hop (payload POST "
            "through decode-side import acknowledgement)",
            registry=self.registry,
        )
        # -- fleet KV tier (peer prefix fetch, models/gateway.py) ----------
        self.serving_kv_peer_fetch_total = Counter(
            "tpu_serving_kv_peer_fetch_total",
            "Peer prefix chains fetched from a ring successor and "
            "imported instead of re-prefilling",
            registry=self.registry,
        )
        self.serving_kv_peer_fetch_failures_total = Counter(
            "tpu_serving_kv_peer_fetch_failures_total",
            "Peer prefix fetches that degraded to local re-prefill "
            "(dead peer, budget, oversized, quarantine, import refusal)",
            registry=self.registry,
        )
        self.serving_kv_peer_bytes_total = Counter(
            "tpu_serving_kv_peer_bytes_total",
            "Serialized chain payload bytes pulled from peers",
            registry=self.registry,
        )
        self.serving_kv_peer_fetch_latency_seconds = Gauge(
            "tpu_serving_kv_peer_fetch_latency_seconds",
            "Duration of the most recent peer fetch (chain pull through "
            "target-side import acknowledgement)",
            registry=self.registry,
        )
        # -- HBM economy (host-RAM block swap, models/paged.py) ------------
        self.serving_kv_swap_out_total = Counter(
            "tpu_serving_kv_swap_out_total",
            "Prefix-chain blocks demoted from the device pool to the "
            "host-RAM swap tier instead of being evicted outright",
            registry=self.registry,
        )
        self.serving_kv_swap_in_total = Counter(
            "tpu_serving_kv_swap_in_total",
            "Swap-resident blocks promoted back into the device pool at "
            "admission or KV import (re-prefill skipped)",
            registry=self.registry,
        )
        self.serving_kv_swap_restored_tokens_total = Counter(
            "tpu_serving_kv_swap_restored_tokens_total",
            "Prompt tokens whose prefill was skipped by a swap restore",
            registry=self.registry,
        )
        self.serving_kv_swap_bytes = Gauge(
            "tpu_serving_kv_swap_bytes",
            "Host RAM currently held by the block-swap tier",
            registry=self.registry,
        )
        # -- speculative decoding (models/speculative.py spec engines) -----
        self.serving_spec_accept_total = Counter(
            "tpu_serving_spec_accept_total",
            "Draft proposals accepted by target verification (each one is "
            "a decode token that cost 1/k of a target forward)",
            registry=self.registry,
        )
        self.serving_spec_rounds_total = Counter(
            "tpu_serving_spec_rounds_total",
            "Speculative draft-verify rounds driven (one fused verify "
            "dispatch per round on the ragged engine)",
            registry=self.registry,
        )
        # -- multi-LoRA serving (models/multilora.py hot-adapter cache) ----
        self.serving_lora_cache_hits_total = Counter(
            "tpu_serving_lora_cache_hits_total",
            "Requests whose adapter was already hot in the replica's "
            "bounded adapter cache",
            registry=self.registry,
        )
        self.serving_lora_cache_misses_total = Counter(
            "tpu_serving_lora_cache_misses_total",
            "Requests that had to load a cold adapter (the cost "
            "(prefix, adapter) affinity routing exists to avoid)",
            registry=self.registry,
        )
        self.serving_lora_cache_evictions_total = Counter(
            "tpu_serving_lora_cache_evictions_total",
            "Adapters evicted from the bounded hot-adapter cache (LRU)",
            registry=self.registry,
        )
        # -- fleet autoscaler (models/autoscaler.py) -----------------------
        self.autoscaler_scale_up_total = Counter(
            "tpu_autoscaler_scale_up_total",
            "Warm-slice claims the autoscaler made on sustained "
            "up-pressure (successful scale-up actions)",
            registry=self.registry,
        )
        self.autoscaler_scale_down_total = Counter(
            "tpu_autoscaler_scale_down_total",
            "Drain-then-release scale-downs the autoscaler initiated on "
            "sustained ebb",
            registry=self.registry,
        )
        self.autoscaler_hold_total = Counter(
            "tpu_autoscaler_hold_total",
            "Desired scale actions suppressed by a guard (cooldown, "
            "rate limit, min/max bound, headroom, claim backoff)",
            registry=self.registry,
        )
        self.autoscaler_freeze_total = Counter(
            "tpu_autoscaler_freeze_total",
            "Freeze episodes: scaling halted on missing or stale "
            "telemetry instead of acting on garbage",
            registry=self.registry,
        )
        self.autoscaler_claim_attempts_total = Counter(
            "tpu_autoscaler_claim_attempts_total",
            "Warm-slice claim attempts issued by the autoscaler",
            registry=self.registry,
        )
        self.autoscaler_claim_failures_total = Counter(
            "tpu_autoscaler_claim_failures_total",
            "Claim attempts that returned nothing (warm pool empty or "
            "claim error) — each starts a jittered backoff",
            registry=self.registry,
        )
        self.autoscaler_claim_latency_seconds = Gauge(
            "tpu_autoscaler_claim_latency_seconds",
            "Wall-clock latency of the most recent warm-slice claim",
            registry=self.registry,
        )
        self.autoscaler_replicas = Gauge(
            "tpu_autoscaler_replicas",
            "In-ring replicas per serving tier as the autoscaler last "
            "counted them",
            ["tier"],
            registry=self.registry,
        )
        # -- live slice migration (runtime/migration.py) -------------------
        self.migration_started_total = Counter(
            "tpu_migration_started_total",
            "Proactive migrations started (preemption notice, idle-cull, "
            "or operator trigger)",
            registry=self.registry,
        )
        self.migration_completed_total = Counter(
            "tpu_migration_completed_total",
            "Migrations that completed all four steps (save, claim, "
            "restore, flip) within their budgets",
            registry=self.registry,
        )
        self.migration_fallback_total = Counter(
            "tpu_migration_fallback_total",
            "Migrations that blew a step budget or hit a step failure and "
            "degraded to the reactive recovery ladder",
            registry=self.registry,
        )
        self.migration_seconds = Gauge(
            "tpu_migration_seconds",
            "Wall-clock duration of the most recent migration attempt "
            "(completed or fallen back)",
            registry=self.registry,
        )
        # -- SLO burn-rate engine (observability/slo.py) -------------------
        # Deliberately outside STATS_PARITY: these are the telemetry
        # plane's own output, surfaced as JSON under /debug/slo rather
        # than the servers' /stats contract.
        self.slo_burn_rate = Gauge(
            "tpu_slo_burn_rate",
            "Error-budget burn rate per SLO objective and window "
            "(1.0 = burning exactly the budget)",
            ["objective", "window"],
            registry=self.registry,
        )
        self.slo_breach_total = Counter(
            "tpu_slo_breach_total",
            "SLO breach alerts latched by the burn-rate engine",
            ["objective"],
            registry=self.registry,
        )

    def collect_running(self) -> None:
        """Recompute run-state gauges by listing StatefulSets, as the
        reference's custom Collector does on scrape (metrics.go:82-99)."""
        if self.client is None:
            return
        running = 0
        chips = 0
        for sts in self.client.list("StatefulSet"):
            replicas = sts.get("spec", {}).get("replicas", 0)
            if replicas > 0:
                running += 1
                template = sts.get("spec", {}).get("template", {}).get("spec", {})
                for c in template.get("containers", []):
                    per_host = int(
                        c.get("resources", {}).get("limits", {}).get("google.com/tpu", 0) or 0
                    )
                    chips += per_host * replicas
        self.running.set(running)
        self.tpu_chips_in_use.set(chips)

    def expose(self) -> bytes:
        """Prometheus text exposition (the /metrics endpoint body)."""
        self.collect_running()
        return generate_latest(self.registry)
