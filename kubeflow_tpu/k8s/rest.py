"""Kind → REST mapping for the real API-server client.

The discovery/RESTMapper role from client-go, reduced to a static table:
every kind the controllers touch, with its group/version/resource and
scope. The reference gets this from scheme registration + discovery
(reference components/notebook-controller/main.go:48-56 registers all
three Notebook versions; client-go's RESTMapper resolves the rest); a
static table keeps the client dependency-free and the mapping auditable.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional
from urllib.parse import quote, urlencode


@dataclass(frozen=True)
class KindInfo:
    group: str  # "" = core
    version: str
    resource: str  # plural, lowercase
    namespaced: bool = True

    @property
    def api_version(self) -> str:
        return self.version if not self.group else f"{self.group}/{self.version}"


# The served (hub) version is what the controllers read and write; the
# conversion webhook / CRD storage handles the rest (kubeflow_tpu.api.notebook
# mirrors reference api/v1beta1/notebook_conversion.go:19's hub choice).
KINDS: dict[str, KindInfo] = {
    # kubeflow.org
    "Notebook": KindInfo("kubeflow.org", "v1beta1", "notebooks"),
    "SlicePool": KindInfo("kubeflow.org", "v1", "slicepools"),
    # core
    "Pod": KindInfo("", "v1", "pods"),
    "Service": KindInfo("", "v1", "services"),
    "ConfigMap": KindInfo("", "v1", "configmaps"),
    "Secret": KindInfo("", "v1", "secrets"),
    "ServiceAccount": KindInfo("", "v1", "serviceaccounts"),
    "Event": KindInfo("", "v1", "events"),
    "Namespace": KindInfo("", "v1", "namespaces", namespaced=False),
    "Node": KindInfo("", "v1", "nodes", namespaced=False),
    # apps
    "StatefulSet": KindInfo("apps", "v1", "statefulsets"),
    "Deployment": KindInfo("apps", "v1", "deployments"),
    # rbac
    "Role": KindInfo("rbac.authorization.k8s.io", "v1", "roles"),
    "RoleBinding": KindInfo("rbac.authorization.k8s.io", "v1", "rolebindings"),
    "ClusterRole": KindInfo(
        "rbac.authorization.k8s.io", "v1", "clusterroles", namespaced=False
    ),
    "ClusterRoleBinding": KindInfo(
        "rbac.authorization.k8s.io", "v1", "clusterrolebindings", namespaced=False
    ),
    # networking
    "NetworkPolicy": KindInfo("networking.k8s.io", "v1", "networkpolicies"),
    # gateway API
    "HTTPRoute": KindInfo("gateway.networking.k8s.io", "v1", "httproutes"),
    "Gateway": KindInfo("gateway.networking.k8s.io", "v1", "gateways"),
    "ReferenceGrant": KindInfo(
        "gateway.networking.k8s.io", "v1beta1", "referencegrants"
    ),
    # coordination (leader election)
    "Lease": KindInfo("coordination.k8s.io", "v1", "leases"),
    # scheduling
    "PriorityClass": KindInfo(
        "scheduling.k8s.io", "v1", "priorityclasses", namespaced=False
    ),
    # apiextensions
    "CustomResourceDefinition": KindInfo(
        "apiextensions.k8s.io", "v1", "customresourcedefinitions", namespaced=False
    ),
    # OpenShift-compatible platform APIs (the platform controller degrades
    # gracefully when these are absent — reference main.go:201-210).
    "APIServer": KindInfo("config.openshift.io", "v1", "apiservers", namespaced=False),
    "Proxy": KindInfo("config.openshift.io", "v1", "proxies", namespaced=False),
    "OAuthClient": KindInfo("oauth.openshift.io", "v1", "oauthclients", namespaced=False),
    "ImageStream": KindInfo("image.openshift.io", "v1", "imagestreams"),
    # Data Science Pipelines operator CR
    "DataSciencePipelinesApplication": KindInfo(
        "datasciencepipelinesapplications.opendatahub.io",
        "v1",
        "datasciencepipelinesapplications",
    ),
}


class UnknownKindError(KeyError):
    pass


def info_for(kind: str) -> KindInfo:
    try:
        return KINDS[kind]
    except KeyError:
        raise UnknownKindError(
            f"kind {kind!r} has no REST mapping; add it to kubeflow_tpu.k8s.rest.KINDS"
        ) from None


def collection_path(kind: str, namespace: str = "") -> str:
    """/api/v1/namespaces/{ns}/pods or /apis/apps/v1/namespaces/{ns}/statefulsets."""
    info = info_for(kind)
    root = "/api/v1" if not info.group else f"/apis/{info.group}/{info.version}"
    if info.namespaced and namespace:
        return f"{root}/namespaces/{quote(namespace)}/{info.resource}"
    return f"{root}/{info.resource}"


def object_path(kind: str, name: str, namespace: str = "") -> str:
    return f"{collection_path(kind, namespace)}/{quote(name)}"


def status_path(kind: str, name: str, namespace: str = "") -> str:
    return f"{object_path(kind, name, namespace)}/status"


def label_selector_str(selector: Optional[dict]) -> str:
    if not selector:
        return ""
    return ",".join(f"{k}={v}" for k, v in sorted(selector.items()))


def list_query(
    label_selector: Optional[dict] = None,
    watch: bool = False,
    resource_version: str = "",
    allow_bookmarks: bool = False,
    timeout_seconds: int = 0,
    field_selector: Optional[dict] = None,
) -> str:
    """Query string for a list or watch request (empty or "?...")."""
    params: list[tuple[str, str]] = []
    sel = label_selector_str(label_selector)
    if sel:
        params.append(("labelSelector", sel))
    if field_selector:
        params.append((
            "fieldSelector",
            ",".join(f"{k}={v}" for k, v in sorted(field_selector.items())),
        ))
    if watch:
        params.append(("watch", "true"))
        if allow_bookmarks:
            params.append(("allowWatchBookmarks", "true"))
    if resource_version:
        params.append(("resourceVersion", resource_version))
    if timeout_seconds:
        params.append(("timeoutSeconds", str(timeout_seconds)))
    if not params:
        return ""
    return "?" + urlencode(params)
