"""Cache memory-footprint transforms.

The ODH manager shrinks its informer cache by stripping the ``data`` payload
of every ConfigMap and Secret it does not actually read (reference
components/odh-notebook-controller/main.go:95-125 — transform funcs keep
data only for objects the reconciler consumes: CA-bundle sources, the
odh-trusted-ca-bundle, runtime-images ConfigMaps, DSPA secrets). This module
provides the same transform as a Client wrapper: reads served through it
return stripped copies unless the object matches a keep-predicate.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.controller.integrations import (
    CA_SOURCE_CONFIGMAPS,
    CA_TARGET_CONFIGMAP,
    RUNTIME_IMAGE_LABEL,
)

STRIPPED_MARK = "kubeflow.org/cache-stripped"

# Names whose payload the platform reconciler / webhook actually reads
# (reference main.go:104-118 keeps exactly these classes of object).
DEFAULT_KEEP_NAMES = frozenset(
    {name for name, _key in CA_SOURCE_CONFIGMAPS}
    | {CA_TARGET_CONFIGMAP, "pipeline-runtime-images"}
)
DEFAULT_KEEP_LABELS = (RUNTIME_IMAGE_LABEL, ann.FEAST_INTEGRATION_LABEL)


def default_keep(obj: dict) -> bool:
    meta = obj.get("metadata", {})
    if meta.get("name", "") in DEFAULT_KEEP_NAMES:
        return True
    labels = meta.get("labels", {})
    if any(label in labels for label in DEFAULT_KEEP_LABELS):
        return True
    # Elyra runtime-config secrets are read to build odh_dsp.json.
    if meta.get("name", "").startswith("ds-pipeline"):
        return True
    return False


def strip_payload(obj: dict, keep: Callable[[dict], bool] = default_keep) -> dict:
    """Strip data/binaryData/stringData from a ConfigMap/Secret copy."""
    if obj.get("kind") not in ("ConfigMap", "Secret") or keep(obj):
        return obj
    stripped = dict(obj)
    for field in ("data", "binaryData", "stringData"):
        stripped.pop(field, None)
    meta = dict(stripped.get("metadata", {}))
    annotations = dict(meta.get("annotations", {}))
    annotations[STRIPPED_MARK] = "true"
    meta["annotations"] = annotations
    stripped["metadata"] = meta
    return stripped


class TransformingClient:
    """Client wrapper applying cache transforms on reads.

    Writes pass through untouched — the transform models what the informer
    cache holds, not what the API server stores.
    """

    def __init__(self, inner: Client, keep: Callable[[dict], bool] = default_keep):
        self.inner = inner
        self.keep = keep

    def get(self, kind: str, name: str, namespace: str = "") -> dict:
        return strip_payload(self.inner.get(kind, name, namespace), self.keep)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        labels: Optional[dict] = None,
        field_selector: Optional[dict] = None,
    ) -> Iterable[dict]:
        return [
            strip_payload(o, self.keep)
            for o in self.inner.list(kind, namespace, labels, field_selector)
        ]

    def create(self, obj: dict) -> dict:
        return self.inner.create(obj)

    def update(self, obj: dict) -> dict:
        return self.inner.update(obj)

    def update_status(self, obj: dict) -> dict:
        return self.inner.update_status(obj)

    def patch(self, kind: str, name: str, namespace: str, patch: dict) -> dict:
        return self.inner.patch(kind, name, namespace, patch)

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        return self.inner.delete(kind, name, namespace)

    def exists(self, kind: str, name: str, namespace: str = "") -> bool:
        return self.inner.exists(kind, name, namespace)
