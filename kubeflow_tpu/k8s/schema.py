"""Structural-schema validation: the apiserver-side CRD schema enforcement.

A real kube-apiserver validates every create/update against the CRD's
openAPIV3Schema (the reference gets this for free from envtest's real
apiserver binaries — suite_test.go:93-303). The EnvtestServer façade uses
this module to enforce the SAME generated schema the repo ships in
``config/crd/bases/``, so controllers cannot write objects a real cluster
would reject with 422.

Implements the subset Kubernetes structural schemas actually use:
``type``, ``properties``, ``required``, ``items``, ``enum``, ``pattern``,
``additionalProperties`` (schema form), ``x-kubernetes-preserve-unknown-
fields``, and numeric bounds. Unknown fields are rejected unless the
schema preserves them (structural-schema pruning semantics, expressed here
as rejection so the writer learns instead of silently losing fields).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Optional

from kubeflow_tpu.k8s.errors import InvalidError

_TYPE_CHECKS = {
    "object": lambda v: isinstance(v, dict),
    "array": lambda v: isinstance(v, list),
    "string": lambda v: isinstance(v, str),
    "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "number": lambda v: isinstance(v, (int, float)) and not isinstance(v, bool),
    "boolean": lambda v: isinstance(v, bool),
}


def validate(obj, schema: dict, path: str = "") -> list[str]:
    """Validate ``obj`` against an openAPIV3Schema node; returns messages
    (empty = valid). Paths are dotted for readability in Status errors."""
    errors: list[str] = []
    where = path or "<root>"
    stype = schema.get("type", "")
    if stype:
        check = _TYPE_CHECKS.get(stype)
        if check and not check(obj):
            errors.append(
                f"{where}: expected {stype}, got {type(obj).__name__}"
            )
            return errors  # deeper checks are meaningless on a type mismatch
    if "enum" in schema and obj not in schema["enum"]:
        allowed = ", ".join(repr(e) for e in schema["enum"][:8])
        errors.append(f"{where}: {obj!r} not one of [{allowed}...]"
                      if len(schema["enum"]) > 8
                      else f"{where}: {obj!r} not one of [{allowed}]")
    if "pattern" in schema and isinstance(obj, str):
        if not re.search(schema["pattern"], obj):
            errors.append(
                f"{where}: {obj!r} does not match pattern {schema['pattern']!r}"
            )
    if isinstance(obj, (int, float)) and not isinstance(obj, bool):
        if "minimum" in schema and obj < schema["minimum"]:
            errors.append(f"{where}: {obj} below minimum {schema['minimum']}")
        if "maximum" in schema and obj > schema["maximum"]:
            errors.append(f"{where}: {obj} above maximum {schema['maximum']}")
    if stype == "object" and isinstance(obj, dict):
        props = schema.get("properties", {})
        for req in schema.get("required", []):
            if req not in obj:
                errors.append(f"{where}: missing required field {req!r}")
        extra_schema = schema.get("additionalProperties")
        preserve = schema.get("x-kubernetes-preserve-unknown-fields", False)
        for key, val in obj.items():
            child_path = f"{path}.{key}" if path else key
            if key in props:
                errors.extend(validate(val, props[key], child_path))
            elif isinstance(extra_schema, dict):
                errors.extend(validate(val, extra_schema, child_path))
            elif preserve or extra_schema is True or not props:
                continue  # free-form subtree
            else:
                errors.append(f"{where}: unknown field {key!r}")
    if stype == "array" and isinstance(obj, list) and "items" in schema:
        for i, item in enumerate(obj):
            errors.extend(validate(item, schema["items"], f"{path}[{i}]"))
    return errors


class CRDSchemas:
    """Per-(kind, version) openAPIV3Schema index loaded from CRD YAMLs."""

    def __init__(self):
        self._by_kind: dict[tuple[str, str], dict] = {}

    @classmethod
    def from_dir(cls, crd_dir: str) -> "CRDSchemas":
        import yaml

        out = cls()
        for p in sorted(Path(crd_dir).glob("*.yaml")):
            for doc in yaml.safe_load_all(p.read_text()):
                if not doc or doc.get("kind") != "CustomResourceDefinition":
                    continue
                kind = doc.get("spec", {}).get("names", {}).get("kind", "")
                group = doc.get("spec", {}).get("group", "")
                for ver in doc.get("spec", {}).get("versions", []):
                    schema = ver.get("schema", {}).get("openAPIV3Schema")
                    if kind and schema and ver.get("served", False):
                        api_version = f"{group}/{ver['name']}"
                        out._by_kind[(kind, api_version)] = schema
        return out

    def schema_for(self, kind: str, api_version: str) -> Optional[dict]:
        return self._by_kind.get((kind, api_version))

    def check(self, obj: dict) -> None:
        """Raise InvalidError (HTTP 422) if ``obj`` violates its schema.
        Objects of kinds/versions without a registered CRD pass through
        (built-in kinds are validated by their own schemas upstream)."""
        schema = self.schema_for(obj.get("kind", ""), obj.get("apiVersion", ""))
        if schema is None:
            return
        # metadata is apimachinery-validated, not CRD-validated; skip it the
        # way a real apiserver does (ObjectMeta has its own schema).
        trimmed = {k: v for k, v in obj.items()
                   if k not in ("metadata", "apiVersion", "kind")}
        errors = validate(trimmed, schema)
        if errors:
            name = obj.get("metadata", {}).get("name", "")
            raise InvalidError(
                f"{obj.get('kind', 'object')} {name!r} is invalid: "
                + "; ".join(errors[:5])
            )
