"""In-memory Kubernetes API server: this project's envtest analog.

The reference's integration tier runs a real kube-apiserver + etcd via
envtest with admission webhooks installed (reference
components/odh-notebook-controller/controllers/suite_test.go:93-303). Without
cluster binaries in this environment, FakeCluster provides the same
contract in-process:

- CRUD with uid / resourceVersion / generation bookkeeping,
- optimistic concurrency (stale resourceVersion → 409 Conflict),
- a status subresource (spec updates can't clobber status and vice versa),
- finalizers + deletionTimestamp two-phase delete,
- cascading garbage collection via ownerReferences,
- registered mutating/validating admission webhooks invoked on create/update,
- an ordered watch-event stream consumed by the Manager.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass
from typing import Callable, Optional

from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.errors import (
    AlreadyExistsError,
    ConflictError,
    ExpiredError,
    InvalidError,
    NotFoundError,
)

CLUSTER_SCOPED_KINDS = {
    "Namespace",
    "Node",
    "ClusterRole",
    "ClusterRoleBinding",
    "CustomResourceDefinition",
    "OAuthClient",
    "Proxy",
    "APIServer",
    "PriorityClass",
}

# Kinds with a status subresource: plain update() preserves stored status.
STATUS_SUBRESOURCE_KINDS = {
    "Notebook",
    "StatefulSet",
    "Deployment",
    "Pod",
    "HTTPRoute",
    "Gateway",
    "DataSciencePipelinesApplication",
}


@dataclass
class AdmissionRequest:
    operation: str  # CREATE | UPDATE | DELETE
    object: dict
    old_object: Optional[dict] = None


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    kind: str
    namespace: str
    name: str
    object: dict


@dataclass
class _Webhook:
    fn: Callable
    operations: tuple[str, ...] = ("CREATE", "UPDATE")


class FakeCluster:
    """Dict-backed API server. Implements the Client protocol."""

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._objects: dict[tuple[str, str, str], dict] = {}
        self._uid = 0
        self._clock = clock or time.time
        self._mutating: dict[str, list[_Webhook]] = {}
        self._validating: dict[str, list[_Webhook]] = {}
        self.events: list[WatchEvent] = []
        self.events_base = 0  # absolute index of events[0] (see compact_events)

    # -- internals ---------------------------------------------------------

    def _key(self, kind: str, name: str, namespace: str) -> tuple[str, str, str]:
        if kind in CLUSTER_SCOPED_KINDS:
            namespace = ""
        return (kind, namespace, name)

    def _now(self) -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(self._clock()))

    def _next_rv(self) -> str:
        """resourceVersions ARE event-log cursors: the object rv stamped
        before an ``_emit`` equals the log cursor AFTER that event, so a
        watch resuming from any object rv replays exactly the events that
        came later — the apiserver contract RealClient's reflector relies
        on when it resumes from the last-seen rv without relisting."""
        return str(self.events_base + len(self.events) + 1)

    def _emit(self, event_type: str, obj: dict) -> None:
        self.events.append(
            WatchEvent(
                event_type,
                obj.get("kind", ""),
                obj_util.namespace_of(obj),
                obj_util.name_of(obj),
                copy.deepcopy(obj),
            )
        )

    def _run_admission(
        self, operation: str, obj: dict, old: Optional[dict]
    ) -> dict:
        kind = obj.get("kind", "")
        req = AdmissionRequest(operation, obj, old)
        for hook in self._mutating.get(kind, []):
            if operation in hook.operations:
                result = hook.fn(req)
                if result is not None:
                    obj = result
                    req = AdmissionRequest(operation, obj, old)
        for hook in self._validating.get(kind, []):
            if operation in hook.operations:
                hook.fn(req)  # raises WebhookDeniedError to deny
        return obj

    # -- webhook registration (envtest WebhookInstallOptions analog) -------

    def register_mutating_webhook(
        self, kind: str, fn: Callable, operations: tuple[str, ...] = ("CREATE", "UPDATE")
    ) -> None:
        self._mutating.setdefault(kind, []).append(_Webhook(fn, operations))

    def register_validating_webhook(
        self, kind: str, fn: Callable, operations: tuple[str, ...] = ("CREATE", "UPDATE")
    ) -> None:
        self._validating.setdefault(kind, []).append(_Webhook(fn, operations))

    # -- Client protocol ---------------------------------------------------

    def get(self, kind: str, name: str, namespace: str = "") -> dict:
        key = self._key(kind, name, namespace)
        try:
            return copy.deepcopy(self._objects[key])
        except KeyError:
            raise NotFoundError(f"{kind} {namespace}/{name} not found") from None

    def list(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[dict] = None,
        field_selector: Optional[dict] = None,
    ) -> list[dict]:
        if kind in CLUSTER_SCOPED_KINDS:
            namespace = ""  # normalize like _key: a ns filter would hide all
        out = []
        for (k, ns, _), obj in sorted(self._objects.items()):
            if k != kind:
                continue
            if namespace and ns != namespace:
                continue
            if not obj_util.matches_labels(obj, label_selector):
                continue
            if not obj_util.matches_fields(obj, field_selector):
                continue
            out.append(copy.deepcopy(obj))
        return out

    def create(self, obj: dict) -> dict:
        obj = copy.deepcopy(obj)
        kind = obj.get("kind", "")
        if not kind or not obj_util.name_of(obj):
            raise InvalidError("object must have kind and metadata.name")
        key = self._key(kind, obj_util.name_of(obj), obj_util.namespace_of(obj))
        if key in self._objects:
            raise AlreadyExistsError(f"{kind} {key[1]}/{key[2]} already exists")
        obj = self._run_admission("CREATE", obj, None)
        # Admission may rewrite name/namespace; store under the final key.
        key = self._key(kind, obj_util.name_of(obj), obj_util.namespace_of(obj))
        if key in self._objects:
            raise AlreadyExistsError(f"{kind} {key[1]}/{key[2]} already exists")
        meta = obj.setdefault("metadata", {})
        self._uid += 1
        meta["uid"] = f"uid-{self._uid}"
        meta["resourceVersion"] = self._next_rv()
        meta["creationTimestamp"] = self._now()
        meta["generation"] = 1
        self._objects[key] = copy.deepcopy(obj)
        self._emit("ADDED", obj)
        return copy.deepcopy(obj)

    def update(self, obj: dict) -> dict:
        obj = copy.deepcopy(obj)
        kind = obj.get("kind", "")
        key = self._key(kind, obj_util.name_of(obj), obj_util.namespace_of(obj))
        stored = self._objects.get(key)
        if stored is None:
            raise NotFoundError(f"{kind} {key[1]}/{key[2]} not found")
        rv = obj.get("metadata", {}).get("resourceVersion")
        if rv is not None and rv != stored["metadata"]["resourceVersion"]:
            raise ConflictError(
                f"{kind} {key[2]}: resourceVersion {rv} is stale "
                f"(current {stored['metadata']['resourceVersion']})"
            )
        obj = self._run_admission("UPDATE", obj, copy.deepcopy(stored))
        meta = obj.setdefault("metadata", {})
        # Immutable/system-managed fields.
        meta["uid"] = stored["metadata"]["uid"]
        meta["creationTimestamp"] = stored["metadata"]["creationTimestamp"]
        if "deletionTimestamp" in stored["metadata"]:
            meta["deletionTimestamp"] = stored["metadata"]["deletionTimestamp"]
        if kind in STATUS_SUBRESOURCE_KINDS and "status" in stored:
            obj["status"] = copy.deepcopy(stored["status"])
        if obj.get("spec") != stored.get("spec"):
            meta["generation"] = stored["metadata"].get("generation", 1) + 1
        else:
            meta["generation"] = stored["metadata"].get("generation", 1)
        # No-op update: nothing changed besides (possibly) the caller echoing
        # back the stored state — skip the event so controllers quiesce.
        meta["resourceVersion"] = stored["metadata"]["resourceVersion"]
        if obj == stored:
            return copy.deepcopy(obj)
        meta["resourceVersion"] = self._next_rv()
        # Deletion completes once finalizers are emptied.
        if "deletionTimestamp" in meta and not meta.get("finalizers"):
            self._remove(key, obj)
            return copy.deepcopy(obj)
        self._objects[key] = copy.deepcopy(obj)
        self._emit("MODIFIED", obj)
        return copy.deepcopy(obj)

    def update_status(self, obj: dict) -> dict:
        kind = obj.get("kind", "")
        key = self._key(kind, obj_util.name_of(obj), obj_util.namespace_of(obj))
        stored = self._objects.get(key)
        if stored is None:
            raise NotFoundError(f"{kind} {key[1]}/{key[2]} not found")
        rv = obj.get("metadata", {}).get("resourceVersion")
        if rv is not None and rv != stored["metadata"]["resourceVersion"]:
            raise ConflictError(f"{kind} {key[2]}: stale resourceVersion on status")
        if stored.get("status", {}) == obj.get("status", {}):
            return copy.deepcopy(stored)  # no-op: no event, no RV bump
        stored = copy.deepcopy(stored)
        stored["status"] = copy.deepcopy(obj.get("status", {}))
        stored["metadata"]["resourceVersion"] = self._next_rv()
        self._objects[key] = stored
        self._emit("MODIFIED", stored)
        return copy.deepcopy(stored)

    def patch(self, kind: str, name: str, namespace: str, patch: dict) -> dict:
        stored = self.get(kind, name, namespace)
        merged = obj_util.merge_patch(stored, patch)
        # Merge patches carry no resourceVersion expectation.
        merged["metadata"]["resourceVersion"] = stored["metadata"]["resourceVersion"]
        return self.update(merged)

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        key = self._key(kind, name, namespace)
        stored = self._objects.get(key)
        if stored is None:
            raise NotFoundError(f"{kind} {namespace}/{name} not found")
        self._run_admission("DELETE", copy.deepcopy(stored), copy.deepcopy(stored))
        meta = stored["metadata"]
        if meta.get("finalizers"):
            if "deletionTimestamp" not in meta:
                meta["deletionTimestamp"] = self._now()
                meta["resourceVersion"] = self._next_rv()
                self._emit("MODIFIED", stored)
            return
        self._remove(key, stored)

    def _remove(self, key: tuple[str, str, str], obj: dict) -> None:
        self._objects.pop(key, None)
        # Deletion is a write: stamp a fresh rv so the DELETED event slots
        # into the log ordering (resuming past it must not replay it).
        obj.setdefault("metadata", {})["resourceVersion"] = self._next_rv()
        self._emit("DELETED", obj)
        self._collect_garbage(obj["metadata"].get("uid"))

    def _collect_garbage(self, owner_uid: Optional[str]) -> None:
        if not owner_uid:
            return
        doomed = [
            (k, o)
            for k, o in list(self._objects.items())
            if any(
                ref.get("uid") == owner_uid
                for ref in o.get("metadata", {}).get("ownerReferences", [])
            )
        ]
        for (kind, ns, name), _ in doomed:
            try:
                self.delete(kind, name, ns)
            except NotFoundError:
                pass

    # -- test conveniences -------------------------------------------------

    def exists(self, kind: str, name: str, namespace: str = "") -> bool:
        return self._key(kind, name, namespace) in self._objects

    def drain_events(self, cursor: int) -> tuple[list[WatchEvent], int]:
        """Events appended since absolute ``cursor``; returns
        (events, new_cursor). Cursors are ABSOLUTE: compaction
        (``compact_events``) advances ``events_base`` without renumbering,
        and a cursor that falls below the compaction horizon raises
        ExpiredError — the apiserver's 410 Gone contract."""
        if cursor < self.events_base:
            raise ExpiredError(
                f"event cursor {cursor} predates compaction horizon "
                f"{self.events_base}"
            )
        start = cursor - self.events_base
        new = self.events[start:]
        return new, self.events_base + len(self.events)

    def event_cursor(self) -> int:
        """Absolute cursor one past the newest event (list resourceVersion)."""
        return self.events_base + len(self.events)

    def compact_events(self, keep_last: int) -> None:
        """Drop all but the newest ``keep_last`` log entries. Watchers
        positioned before the new horizon get ExpiredError (→ 410 Gone)
        on their next drain and must relist. Bounds the log's memory for
        long-running servers (a real apiserver compacts etcd the same way)."""
        drop = max(0, len(self.events) - keep_last)
        if drop:
            del self.events[:drop]
            self.events_base += drop
