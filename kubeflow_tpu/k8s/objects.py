"""Helpers over dict-shaped Kubernetes objects.

Objects are plain dicts in the exact JSON shape the real API server uses
(``{"apiVersion": ..., "kind": ..., "metadata": {...}, "spec": {...}}``), so
manifests, fixtures and admission payloads round-trip without a typed layer.
These helpers cover the apimachinery idioms the reference leans on:
controller references (controllerutil.SetControllerReference), label-selector
matching, and JSON merge patch (RFC 7386, as used by
client.RawPatch(types.MergePatchType, ...) in reference
components/odh-notebook-controller/controllers/notebook_controller.go:155-186).
"""

from __future__ import annotations

import copy
from typing import Any, Optional


def name_of(obj: dict) -> str:
    return obj.get("metadata", {}).get("name", "")


def namespace_of(obj: dict) -> str:
    return obj.get("metadata", {}).get("namespace", "")


def uid_of(obj: dict) -> str:
    return obj.get("metadata", {}).get("uid", "")


def labels_of(obj: dict) -> dict:
    return obj.setdefault("metadata", {}).setdefault("labels", {})


def annotations_of(obj: dict) -> dict:
    return obj.setdefault("metadata", {}).setdefault("annotations", {})


def get_annotation(obj: dict, key: str, default: Optional[str] = None) -> Optional[str]:
    return obj.get("metadata", {}).get("annotations", {}).get(key, default)


def set_annotation(obj: dict, key: str, value: str) -> None:
    annotations_of(obj)[key] = value


def remove_annotation(obj: dict, key: str) -> bool:
    anns = obj.get("metadata", {}).get("annotations", {})
    if key in anns:
        del anns[key]
        return True
    return False


def new_object(
    api_version: str,
    kind: str,
    name: str,
    namespace: str = "",
    labels: Optional[dict] = None,
    annotations: Optional[dict] = None,
) -> dict:
    meta: dict[str, Any] = {"name": name}
    if namespace:
        meta["namespace"] = namespace
    if labels:
        meta["labels"] = dict(labels)
    if annotations:
        meta["annotations"] = dict(annotations)
    return {"apiVersion": api_version, "kind": kind, "metadata": meta}


# ---------------------------------------------------------------------------
# Owner references


def set_controller_reference(owner: dict, obj: dict) -> None:
    """Mark ``obj`` as controlled by ``owner`` (controllerutil semantics)."""
    refs = obj.setdefault("metadata", {}).setdefault("ownerReferences", [])
    for ref in refs:
        if ref.get("controller") and ref.get("uid") != uid_of(owner):
            raise ValueError(
                f"{name_of(obj)} already controlled by {ref.get('name')}"
            )
    ref = {
        "apiVersion": owner.get("apiVersion", ""),
        "kind": owner.get("kind", ""),
        "name": name_of(owner),
        "uid": uid_of(owner),
        "controller": True,
        "blockOwnerDeletion": True,
    }
    refs[:] = [r for r in refs if r.get("uid") != ref["uid"]] + [ref]


def set_owner_reference(owner: dict, obj: dict) -> None:
    """Non-controller owner reference (GC only)."""
    refs = obj.setdefault("metadata", {}).setdefault("ownerReferences", [])
    if not any(r.get("uid") == uid_of(owner) for r in refs):
        refs.append(
            {
                "apiVersion": owner.get("apiVersion", ""),
                "kind": owner.get("kind", ""),
                "name": name_of(owner),
                "uid": uid_of(owner),
            }
        )


def owner_uid(obj: dict) -> Optional[str]:
    """UID of the controlling owner, if any."""
    for ref in obj.get("metadata", {}).get("ownerReferences", []):
        if ref.get("controller"):
            return ref.get("uid")
    return None


def is_controlled_by(owner: dict, obj: dict) -> bool:
    return owner_uid(obj) == uid_of(owner) and uid_of(owner) != ""


# ---------------------------------------------------------------------------
# Selectors and patch


def matches_labels(obj: dict, selector: Optional[dict]) -> bool:
    if not selector:
        return True
    labels = obj.get("metadata", {}).get("labels", {})
    return all(labels.get(k) == v for k, v in selector.items())


def matches_fields(obj: dict, selector: Optional[dict]) -> bool:
    """fieldSelector equality over dotted paths (the apiserver's indexed
    subset, e.g. ``involvedObject.name=wb-0`` on Events)."""
    if not selector:
        return True
    for path, want in selector.items():
        cur = obj
        for part in path.split("."):
            if not isinstance(cur, dict):
                cur = None
                break
            cur = cur.get(part)
        if cur != want:
            return False
    return True


def merge_patch(obj: dict, patch: dict) -> dict:
    """Apply an RFC 7386 JSON merge patch, returning a new object."""
    result = copy.deepcopy(obj)
    _merge_into(result, patch)
    return result


def _merge_into(target: dict, patch: dict) -> None:
    for key, value in patch.items():
        if value is None:
            target.pop(key, None)
        elif isinstance(value, dict) and isinstance(target.get(key), dict):
            _merge_into(target[key], value)
        else:
            target[key] = copy.deepcopy(value)


# ---------------------------------------------------------------------------
# Conditions (metav1.Condition idiom)


def get_condition(obj: dict, cond_type: str) -> Optional[dict]:
    for c in obj.get("status", {}).get("conditions", []):
        if c.get("type") == cond_type:
            return c
    return None


def set_condition(obj: dict, condition: dict, now: Optional[str] = None) -> None:
    """Upsert a condition by type (meta.SetStatusCondition semantics).

    ``lastTransitionTime`` is stamped when the condition first appears or its
    status flips; unchanged statuses keep the previous transition time.
    """
    if now is None:
        import time

        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    conds = obj.setdefault("status", {}).setdefault("conditions", [])
    for i, c in enumerate(conds):
        if c.get("type") == condition.get("type"):
            if (
                c.get("status") == condition.get("status")
                and c.get("reason") == condition.get("reason")
                and c.get("message") == condition.get("message")
            ):
                return
            if c.get("status") == condition.get("status"):
                condition.setdefault(
                    "lastTransitionTime", c.get("lastTransitionTime", now)
                )
            else:
                condition["lastTransitionTime"] = now
            conds[i] = condition
            return
    condition.setdefault("lastTransitionTime", now)
    conds.append(condition)


def parse_timestamp(ts) -> "float | None":
    """RFC3339 apiserver timestamp → epoch seconds, None if unparseable.

    The ONE home for this parse (culling idleness math, spawn-latency
    metrics, pre-pull retry backoff all consume apiserver timestamps);
    a format tolerance added here reaches every consumer."""
    import calendar
    import time

    try:
        return float(calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ")))
    except (ValueError, TypeError, OverflowError):
        return None
