"""Kubernetes Event recording (client-go record.EventRecorder analog).

The reference leans on Events for user-visible failure diagnosis — both
emitting its own (reference notebook_mlflow.go:259-260) and *re-emitting*
pod/STS events onto the Notebook CR so users see scheduling failures without
kubectl-describing child objects (reference
components/notebook-controller/controllers/notebook_controller.go:99-126).
"""

from __future__ import annotations

import hashlib
from typing import Optional

from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.errors import AlreadyExistsError, NotFoundError


class EventRecorder:
    def __init__(self, client: Client, component: str = "notebook-controller"):
        self.client = client
        self.component = component

    def eventf(
        self,
        obj: dict,
        event_type: str,  # Normal | Warning
        reason: str,
        message: str,
    ) -> dict:
        """Create (or bump the count of) an Event for ``obj``."""
        namespace = obj.get("metadata", {}).get("namespace", "default")
        involved = {
            "apiVersion": obj.get("apiVersion", ""),
            "kind": obj.get("kind", ""),
            "name": obj.get("metadata", {}).get("name", ""),
            "namespace": namespace,
            "uid": obj.get("metadata", {}).get("uid", ""),
        }
        digest = hashlib.sha1(
            f"{involved['kind']}/{involved['name']}/{reason}/{message}".encode()
        ).hexdigest()[:10]
        name = f"{involved['name']}.{digest}"
        try:
            existing = self.client.get("Event", name, namespace)
            existing["count"] = existing.get("count", 1) + 1
            return self.client.update(existing)
        except NotFoundError:
            pass
        event = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": name, "namespace": namespace},
            "involvedObject": involved,
            "type": event_type,
            "reason": reason,
            "message": message,
            "count": 1,
            "source": {"component": self.component},
        }
        try:
            return self.client.create(event)
        except AlreadyExistsError:
            return event


def events_for(client: Client, kind: str, name: str, namespace: str) -> list[dict]:
    """All Events whose involvedObject matches (test/diagnosis helper)."""
    out = []
    for ev in client.list("Event", namespace):
        inv = ev.get("involvedObject", {})
        if inv.get("kind") == kind and inv.get("name") == name:
            out.append(ev)
    return out
