"""Declarative chaos experiment catalog: schema validation + execution.

The reference keeps a catalog of declarative ChaosExperiment CRs
(reference chaos/experiments/*.yaml — pod-kill, network-partition,
deployment-scale-zero, rbac-revoke, webhook-disrupt) that CI only
schema-validates (.github/workflows/operator_chaos_validation.yaml:63-67);
actually running them needs a live cluster + chaos operator. Because this
project's API server is in-process, the same catalog is *executable*: the
runner interprets each injection type against a FakeCluster + Manager
environment and asserts the steady-state checks recover.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable

import yaml

from kubeflow_tpu.api import annotations as ann

EXPERIMENT_KIND = "ChaosExperiment"
KNOWLEDGE_KIND = "KnowledgeModel"
API_VERSION = "chaos.kubeflow.org/v1alpha1"

INJECTION_TYPES = (
    "pod-kill",
    "network-partition",
    "controller-outage",
    "client-fault",
    "webhook-error",
    "placeholder-kill",
    # Recovery escalation coverage (controller/preemption.py): repeated
    # kills mid-recovery, capacity that never comes back, and an apiserver
    # that flaps while the escalation ladder runs. Each must converge to
    # SliceRecovered or the terminal SliceRecoveryFailed condition — a
    # silent stall is the one outcome the state machine exists to forbid.
    "preemption-storm",
    "capacity-withheld",
    "apiserver-flap",
    # Serving request-lifecycle coverage (models/server.py): the in-pod
    # inference front door under client misbehavior. Disconnecting
    # streamers must free their slots within one engine step, a full
    # pending queue must shed (429) instead of parking handler threads,
    # and a crashed engine thread must abort waiters loudly — never a
    # slot decoding for nobody or a client hung forever.
    "serving-disconnect-storm",
    "serving-overload",
    "serving-engine-stall",
    # Checkpoint durability coverage (runtime/checkpoint.py): a crash in
    # the middle of an async save, bit-rot/truncation of the newest step,
    # and a disk that fills mid-training. Each must leave training
    # RESUMABLE from the newest valid step with zero loss-curve
    # divergence — a torn or silently-wrong "latest" is the one outcome
    # the atomic-commit protocol exists to forbid.
    "checkpoint-kill-mid-save",
    "checkpoint-restore-corrupt",
    "checkpoint-disk-full",
    # Fleet gateway coverage (models/gateway.py): a replica pod dies
    # abruptly mid-stream. The error burst must be bounded to exactly the
    # streams in flight on the dead replica (each terminated with a
    # distinguishable error event, never silent truncation), the hash
    # ring must heal within the probe interval, and post-heal traffic
    # must succeed with zero further failures.
    "gateway-replica-kill",
    # Disaggregated serving coverage (models/gateway.py tier routing):
    # the prefill-tier replica dies mid-KV-export with a handoff in
    # flight. The gateway must re-route the request to a surviving
    # prefill replica within the re-route budget (or surface an explicit
    # error event before [DONE] — silent truncation is the one outcome
    # forbidden), drop the dead replica from the ring, and leave the
    # decode tier untouched: post-heal traffic keeps streaming through
    # the paged-KV handoff with zero transfer failures.
    "serving-kv-handoff-loss",
    # Fleet KV tier coverage (models/gateway.py peer prefix fetch): the
    # peer that answered /kv/probe with a full-chain match dies mid-way
    # through the /kv/chain export, leaving the gateway a torn payload
    # with a client stream already open. The fetch ladder must degrade
    # to a plain re-prefill on the routed replica — the client still
    # gets every token and [DONE], never an error or silent truncation
    # — the dead peer must land in the negative cache (no repeat probes
    # while it holds), and the ring must heal. A peer-tier failure that
    # becomes client-visible is the outcome the ladder exists to forbid.
    "serving-kv-peer-loss",
    # Fleet autoscaler coverage (models/autoscaler.py): scale-down under
    # stream churn. The autoscaler drains the least-loaded replica while
    # slow streams are in flight across the fleet; the drained replica
    # must leave the ring immediately (no new routes) yet keep serving
    # its in-flight streams, the slice must be released only after those
    # streams finish (within the drain budget), and the whole storm must
    # end with every stream terminating in [DONE], zero error events,
    # and zero tenants shed — killing an active stream or shedding an
    # under-share tenant is the outcome scale-down exists to forbid.
    "autoscaler-scaledown-storm",
    # Live slice migration (runtime/migration.py): repeated preemption
    # notices against a live tiny trainer, each driving the full save →
    # warm-claim → restore → flip pipeline. Training throughput may dip
    # during a migration but must never zero, every migration must resume
    # token/loss-exact (the checkpoint experiments' zero-divergence
    # assertion), the old slice must release drain-style only after the
    # flip, and each migration must read as ONE complete trace with a
    # span per step — a migration that hangs, loses work, or silently
    # degrades is the outcome the budgeted pipeline exists to forbid.
    "migration-storm",
)
STEADY_STATE_CHECKS = (
    "sliceReady", "notCulled", "notebookCreatable", "warmPoolReady",
    # Recovery reached SliceRecovered or the terminal condition — never a
    # silent stall with an interrupted slice and no requeue.
    "recoveryConverged",
    # Serving: /healthz answers 200 and the engine thread is alive.
    "servingHealthy",
    # Serving: no slot (or queue entry) still holds work for a client
    # that is gone — the disconnect-storm invariant.
    "slotsReclaimed",
    # Checkpoint: the newest COMMITTED step re-validates (manifest sizes +
    # checksums) after the injection.
    "checkpointValid",
    # Checkpoint: a restore + continued training reproduces the
    # uninterrupted run's loss curve exactly.
    "trainingResumed",
    # Gateway: the dead replica left the ring, survivors serve, and the
    # failed-stream count equals the in-flight burst — no silent loss.
    "gatewayHealed",
    # Disaggregated serving: the decode tier answers /healthz, stays in
    # the ring, and keeps importing KV payloads after a prefill-tier
    # loss — tier failure must not cascade across the handoff boundary.
    "decodeTierHealthy",
    # Fleet KV tier: every failed peer fetch degraded to re-prefill
    # with zero client-visible failures, and the dead peer is
    # negative-cached so the ladder stops probing a corpse.
    "peerFetchDegraded",
    # Autoscaler scale-down: every in-flight stream on a draining
    # replica ran to [DONE] and its slice was released only afterwards.
    "streamsDrained",
    # Live migration: every triggered migration completed all four
    # budgeted steps as one trace, training resumed loss-exact on the
    # new slice, and the old slice drained only after the flip.
    "migrationComplete",
)
# Injection ↔ target coherence: a doc must declare the kind its handler
# actually exercises, or a "pass" certifies a hypothesis that never ran.
TARGET_KIND_FOR_INJECTION = {
    "pod-kill": "Notebook",
    "network-partition": "Notebook",
    "controller-outage": "Notebook",
    "client-fault": "Notebook",
    "webhook-error": "Notebook",
    "placeholder-kill": "SlicePool",
    "preemption-storm": "Notebook",
    "capacity-withheld": "Notebook",
    "apiserver-flap": "Notebook",
    "serving-disconnect-storm": "InferenceServer",
    "serving-overload": "InferenceServer",
    "serving-engine-stall": "InferenceServer",
    "checkpoint-kill-mid-save": "CheckpointManager",
    "checkpoint-restore-corrupt": "CheckpointManager",
    "checkpoint-disk-full": "CheckpointManager",
    "gateway-replica-kill": "ServingGateway",
    "serving-kv-handoff-loss": "ServingGateway",
    "serving-kv-peer-loss": "ServingGateway",
    "autoscaler-scaledown-storm": "ServingGateway",
    "migration-storm": "MigrationOrchestrator",
}


class ValidationError(ValueError):
    pass


def load_documents(path: Path) -> list[dict]:
    return [d for d in yaml.safe_load_all(path.read_text()) if d]


def load_experiments(directory: Path) -> list[dict]:
    docs = []
    for path in sorted(directory.glob("*.yaml")):
        docs.extend(load_documents(path))
    return docs


def validate_experiment(doc: dict) -> None:
    """Schema validation (the reference CI's validation step)."""

    def need(cond: bool, msg: str) -> None:
        if not cond:
            raise ValidationError(f"{doc.get('metadata', {}).get('name', '?')}: {msg}")

    need(doc.get("apiVersion") == API_VERSION, f"apiVersion must be {API_VERSION}")
    need(doc.get("kind") == EXPERIMENT_KIND, f"kind must be {EXPERIMENT_KIND}")
    need(bool(doc.get("metadata", {}).get("name")), "metadata.name required")
    spec = doc.get("spec", {})
    states = spec.get("steadyState", [])
    need(len(states) > 0, "at least one steadyState check")
    for st in states:
        need(st.get("check") in STEADY_STATE_CHECKS, f"unknown check {st.get('check')}")
    injection = spec.get("injection", {})
    need(injection.get("type") in INJECTION_TYPES, f"unknown injection {injection.get('type')}")
    want_kind = TARGET_KIND_FOR_INJECTION[injection["type"]]
    need(
        spec.get("target", {}).get("kind") == want_kind,
        f"injection {injection['type']} targets {want_kind}, "
        f"got target.kind {spec.get('target', {}).get('kind')}",
    )
    need(bool(spec.get("hypothesis")), "hypothesis required")
    need(
        isinstance(spec.get("recoveryTimeoutSeconds"), int)
        and spec["recoveryTimeoutSeconds"] > 0,
        "recoveryTimeoutSeconds must be a positive int",
    )
    need(
        spec.get("blastRadius", {}).get("scope") in ("namespace", "cluster"),
        "blastRadius.scope must be namespace|cluster",
    )


def validate_knowledge(doc: dict) -> None:
    def need(cond: bool, msg: str) -> None:
        if not cond:
            raise ValidationError(msg)

    need(doc.get("kind") == KNOWLEDGE_KIND, f"kind must be {KNOWLEDGE_KIND}")
    spec = doc.get("spec", {})
    controllers = {c.get("name") for c in spec.get("controllers", [])}
    need(
        controllers == {"notebook-controller", "platform-notebook-controller"},
        f"controllers must list both managers, got {controllers}",
    )
    for c in spec.get("controllers", []):
        need(bool(c.get("watches")), f"{c['name']}: watches required")
        need(bool(c.get("managedResources")), f"{c['name']}: managedResources required")
        for r in c["managedResources"]:
            need(bool(r.get("kind")), f"{c['name']}: managedResource without kind")
    hooks = {w.get("path") for w in spec.get("webhooks", [])}
    need(
        hooks == {"/mutate-notebook-v1", "/validate-notebook-v1"},
        f"webhooks must cover both admission paths, got {hooks}",
    )


# ---------------------------------------------------------------------------
# Execution


def _default_serving_factory(**kw):
    """Tiny CPU-model serving stack for the serving-* experiments. The
    model imports are lazy: catalog *validation* (the CI path) must not
    require the jax stack."""
    import jax

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.continuous import ContinuousBatcher
    from kubeflow_tpu.models.server import InferenceServer
    from kubeflow_tpu.models.serving import GenerationConfig

    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    engine = ContinuousBatcher(
        params, cfg,
        slots=kw.pop("slots", 2),
        cache_len=128,
        prompt_bucket=16,
        gen=GenerationConfig(max_new_tokens=kw.pop("max_new_tokens", 64)),
    )
    # Short drain: experiment teardown must not wait a full production
    # drain window for work the experiment itself orphaned.
    kw.setdefault("drain_s", 0.5)
    return InferenceServer(engine, port=0, **kw)


_TINY_TRAINER = None


def _default_training_factory():
    """Deterministic tiny-llama trainer for the checkpoint-* experiments
    (models.train.make_tiny_trainer). Lazy jax import for the same reason
    as the serving factory, and memoized: the three checkpoint handlers
    share one jitted step so the catalog does not recompile per run (the
    trainer is stateless — each handler builds fresh states from it)."""
    global _TINY_TRAINER
    if _TINY_TRAINER is None:
        from kubeflow_tpu.models.train import make_tiny_trainer

        _TINY_TRAINER = make_tiny_trainer()
    return _TINY_TRAINER


def _counter_value(counter) -> float:
    """Current value of a prometheus Counter via its public collect()."""
    for metric in counter.collect():
        for sample in metric.samples:
            if sample.name.endswith("_total"):
                return sample.value
    return 0.0


class _SimulatedCrash(Exception):
    """Raised by fault-injecting CheckpointIO to model a SIGKILL landing
    mid-save: save() deliberately does NOT catch it (only OSError), so the
    staging dir is left exactly as a dead process would leave it."""


class _CrashableReplica:
    """Minimal replica speaking the InferenceServer HTTP contract
    (healthz / stats / streaming completions) with one extra affordance a
    real server cannot offer in-process: ``crash()`` severs the listening
    socket AND every accepted connection at once — what a SIGKILLed pod
    looks like from the gateway's side of the wire. The gateway is the
    system under test here; the engine behind the replica is not."""

    def __init__(self, *, tokens: int = 40, token_delay_s: float = 0.05):
        import socket as socket_mod
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        self.tokens = tokens
        self.token_delay_s = token_delay_s
        self.lock = threading.Lock()
        self.inflight = 0
        self.served = 0
        self.conns: set = set()
        self._socket_mod = socket_mod
        replica = self

        class QuietServer(ThreadingHTTPServer):
            daemon_threads = True

            def handle_error(self, request, client_address):
                pass  # crash() severs sockets mid-write by design

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _json(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, {"status": "ok"})
                elif self.path == "/stats":
                    with replica.lock:
                        self._json(200, {
                            "slots": 4,
                            "active_slots": replica.inflight,
                            "queued": 0,
                            "served": replica.served,
                        })
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                with replica.lock:
                    replica.conns.add(self.connection)
                    replica.inflight += 1
                done = False
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    if req.get("stream"):
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/event-stream")
                        self.send_header("Connection", "close")
                        self.end_headers()
                        for t in range(replica.tokens):
                            time.sleep(replica.token_delay_s)
                            self.wfile.write(
                                b"data: "
                                + json.dumps({"token": t}).encode()
                                + b"\n\n"
                            )
                            self.wfile.flush()
                        self.wfile.write(b"data: [DONE]\n\n")
                        self.wfile.flush()
                    else:
                        self._json(200, {
                            "id": "cmpl-0",
                            "object": "text_completion",
                            "choices": [{"index": 0, "tokens": [0, 1],
                                         "finish_reason": "stop"}],
                            "usage": {},
                        })
                    # Retire under the lock the moment [DONE] is on the
                    # wire: a crash() racing this stream's completion
                    # must not count it as severed.
                    with replica.lock:
                        replica.served += 1
                        replica.inflight -= 1
                        replica.conns.discard(self.connection)
                        done = True
                finally:
                    if not done:
                        with replica.lock:
                            replica.inflight -= 1
                            replica.conns.discard(self.connection)

        self.httpd = QuietServer(("127.0.0.1", 0), Handler)
        self.host, self.port = self.httpd.server_address[:2]
        self.endpoint = f"{self.host}:{self.port}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.crashed = False

    def start(self) -> "_CrashableReplica":
        self.thread.start()
        return self

    def crash(self) -> int:
        """Abrupt death: returns the number of streams severed."""
        with self.lock:
            self.crashed = True
            severed = list(self.conns)
        self.httpd.shutdown()
        self.httpd.server_close()
        for sock in severed:
            try:
                sock.shutdown(self._socket_mod.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        return len(severed)

    def stop(self) -> None:
        if not self.crashed:
            self.crash()


class _DrainableReplica(_CrashableReplica):
    """A :class:`_CrashableReplica` with the PR 2 drain lifecycle the
    autoscaler's scale-down exercises: ``drain()`` flips /healthz to
    503 {"status": "draining"} immediately (the gateway must stop
    routing here) while every in-flight stream runs to its natural
    ``[DONE]``; new completions are refused like a real draining
    InferenceServer. ``release()`` tears the listener down and records
    how many streams it severed — a correct autoscaler releases only
    after the drain emptied, so that count must be zero."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.draining = False
        self.severed_at_release = -1
        replica = self
        inner_get = self.httpd.RequestHandlerClass.do_GET
        inner_post = self.httpd.RequestHandlerClass.do_POST

        class Handler(self.httpd.RequestHandlerClass):
            def do_GET(self):
                if self.path == "/healthz" and replica.draining:
                    self._json(503, {"status": "draining"})
                else:
                    inner_get(self)

            def do_POST(self):
                if replica.draining:
                    self._json(503, {"error": "draining"})
                else:
                    inner_post(self)

        self.httpd.RequestHandlerClass = Handler

    def drain(self) -> None:
        with self.lock:
            self.draining = True

    @property
    def drained(self) -> bool:
        with self.lock:
            return self.draining and self.inflight == 0

    def release(self) -> None:
        """Slice teardown; anything still on the wire here was killed
        by a premature release."""
        self.severed_at_release = self.crash()


class _CrashablePrefill:
    """Minimal prefill-tier replica for the disaggregated fleet: answers
    /healthz and /stats like an InferenceServer, then dies mid-export on
    its first ``/kv/prefill`` — response headers and a torn body are on
    the wire when the listener goes down. That is what a SIGKILLed
    prefill pod looks like from the gateway's side of the KV handoff;
    the gateway's re-route walk is the system under test, so the engine
    behind this replica never needs to exist."""

    def __init__(self):
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        self.lock = threading.Lock()
        self.hits = 0
        self.crashed = False
        replica = self

        class QuietServer(ThreadingHTTPServer):
            daemon_threads = True

            def handle_error(self, request, client_address):
                pass  # crash() tears sockets mid-write by design

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _json(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, {"status": "ok"})
                elif self.path == "/stats":
                    self._json(200, {"slots": 2, "active_slots": 0,
                                     "queued": 0, "served": 0,
                                     "tier_role": "prefill"})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                self.rfile.read(length)
                if self.path != "/kv/prefill":
                    self._json(404, {"error": "not found"})
                    return
                with replica.lock:
                    replica.hits += 1
                # Die mid-export: declare a body, ship a fragment of it,
                # then take the whole pod down — the gateway reads an
                # IncompleteRead off this socket, not a clean refusal.
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", "4096")
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(b'{"payload": {"blocks": [')
                self.wfile.flush()
                replica.crash()

        self.httpd = QuietServer(("127.0.0.1", 0), Handler)
        self.host, self.port = self.httpd.server_address[:2]
        self.endpoint = f"{self.host}:{self.port}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    def start(self) -> "_CrashablePrefill":
        self.thread.start()
        return self

    def crash(self) -> None:
        with self.lock:
            if self.crashed:
                return
            self.crashed = True
        self.httpd.shutdown()
        self.httpd.server_close()

    def stop(self) -> None:
        self.crash()


class _CrashablePeer:
    """Fused-fleet peer replica for the peer-prefix-fetch experiment:
    healthy on /healthz, answers ``/kv/probe`` with a full-chain match
    (the bait), then dies mid-body on the ``/kv/chain`` pull — torn
    export on the wire, pod gone. That is a peer SIGKILLed between the
    probe and the pull; the gateway's degrade-to-re-prefill ladder is
    the system under test, so no engine lives behind this replica."""

    def __init__(self):
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        self.lock = threading.Lock()
        self.probe_hits = 0
        self.chain_hits = 0
        self.crashed = False
        replica = self

        class QuietServer(ThreadingHTTPServer):
            daemon_threads = True

            def handle_error(self, request, client_address):
                pass  # crash() tears sockets mid-write by design

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _json(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, {"status": "ok"})
                elif self.path == "/stats":
                    self._json(200, {"slots": 2, "active_slots": 0,
                                     "queued": 0, "served": 0,
                                     "tier_role": "fused"})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if self.path == "/kv/probe":
                    with replica.lock:
                        replica.probe_hits += 1
                    try:
                        keys = json.loads(body).get("keys", [])
                    except ValueError:
                        keys = []
                    # Full-chain bait: deep enough to beat whatever the
                    # target holds, small enough to clear the byte cap.
                    self._json(200, {"matched": len(keys),
                                     "block_bytes": 2048,
                                     "payload_bytes": 4096})
                    return
                if self.path != "/kv/chain":
                    self._json(404, {"error": "not found"})
                    return
                with replica.lock:
                    replica.chain_hits += 1
                # Die mid-export: declare a body, ship a fragment, take
                # the pod down — the gateway reads a torn payload, not a
                # clean refusal.
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", "4096")
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(b'{"matched": 2, "payload": {"blo')
                self.wfile.flush()
                replica.crash()

        self.httpd = QuietServer(("127.0.0.1", 0), Handler)
        self.host, self.port = self.httpd.server_address[:2]
        self.endpoint = f"{self.host}:{self.port}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    def start(self) -> "_CrashablePeer":
        self.thread.start()
        return self

    def crash(self) -> None:
        with self.lock:
            if self.crashed:
                return
            self.crashed = True
        self.httpd.shutdown()
        self.httpd.server_close()

    def stop(self) -> None:
        self.crash()


def _paged_serving_factory(*, tier_role: str):
    """Tiny paged-engine serving stack for the disaggregated-fleet
    experiments: prefix_cache on (KV export/import requires the chain
    index), lazy jax imports for the same reason as the default
    factory."""
    import jax

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.paged import PagedBatcher
    from kubeflow_tpu.models.server import InferenceServer
    from kubeflow_tpu.models.serving import GenerationConfig

    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    engine = PagedBatcher(
        params, cfg,
        gen=GenerationConfig(max_new_tokens=16, eos_id=-1),
        slots=2, num_blocks=32, block_size=8, prompt_bucket=16,
        prefix_cache=True,
    )
    return InferenceServer(engine, port=0, drain_s=0.5,
                           tier_role=tier_role)


def _serving_get(port: int, path: str, timeout: float = 60.0):
    """(status, body) for a replica GET — health and stats scrapes."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, {}
    except (OSError, ValueError):
        return 0, {}


def _serving_post(port: int, payload: dict, timeout: float = 60.0):
    """(status, body) for a completions POST — HTTPError is an outcome
    here (429/503/500 are the behaviors under test), not an exception."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        try:
            body = json.loads(err.read())
        except Exception:
            body = {}
        return err.code, body


@dataclass
class ExperimentResult:
    name: str
    passed: bool
    detail: str = ""
    observations: dict = field(default_factory=dict)


class ExperimentRunner:
    """Executes catalog experiments against a harness environment.

    The runner owns no cluster itself: callers hand it an ``env_factory``
    producing the envtest-style environment (tests/harness.make_env shape:
    cluster, manager, clock, kubelet, culler/prober when culling is on) and
    a fresh environment is built per experiment — blast radius never leaks
    across runs.
    """

    def __init__(self, env_factory: Callable[..., object],
                 notebook_factory: Callable[..., dict],
                 serving_factory: Callable[..., object] = None,
                 training_factory: Callable[..., object] = None):
        self.env_factory = env_factory
        self.notebook_factory = notebook_factory
        # serving_factory(**knobs) -> an UNstarted models/server.py
        # InferenceServer over a tiny engine; the serving-* handlers
        # start/stop it per experiment. Defaults to a tiny CPU model so
        # the catalog stays executable without the caller wiring one.
        self.serving_factory = serving_factory or _default_serving_factory
        # training_factory() -> (step_fn, fresh_state, batches) for the
        # checkpoint-* handlers; defaults to the shared tiny trainer.
        self.training_factory = training_factory or _default_training_factory
        self._handlers: dict[str, Callable[[dict], ExperimentResult]] = {
            "pod-kill": self._run_pod_kill,
            "network-partition": self._run_network_partition,
            "controller-outage": self._run_controller_outage,
            "client-fault": self._run_client_fault,
            "webhook-error": self._run_webhook_error,
            "placeholder-kill": self._run_placeholder_kill,
            "preemption-storm": self._run_preemption_storm,
            "capacity-withheld": self._run_capacity_withheld,
            "apiserver-flap": self._run_apiserver_flap,
            "serving-disconnect-storm": self._run_serving_disconnect_storm,
            "serving-overload": self._run_serving_overload,
            "serving-engine-stall": self._run_serving_engine_stall,
            "checkpoint-kill-mid-save": self._run_checkpoint_kill_mid_save,
            "checkpoint-restore-corrupt": self._run_checkpoint_restore_corrupt,
            "checkpoint-disk-full": self._run_checkpoint_disk_full,
            "gateway-replica-kill": self._run_gateway_replica_kill,
            "serving-kv-handoff-loss": self._run_serving_kv_handoff_loss,
            "serving-kv-peer-loss": self._run_serving_kv_peer_loss,
            "autoscaler-scaledown-storm":
                self._run_autoscaler_scaledown_storm,
            "migration-storm": self._run_migration_storm,
        }

    def run(self, doc: dict) -> ExperimentResult:
        validate_experiment(doc)
        handler = self._handlers[doc["spec"]["injection"]["type"]]
        return handler(doc)

    # -- shared helpers ----------------------------------------------------

    def _ready_slice(self, env, name: str = "nb") -> dict:
        nb = self.notebook_factory(name=name)
        env.cluster.create(nb)
        env.manager.run_until_idle()
        return env.cluster.get("Notebook", name, "ns")

    @staticmethod
    def _slice_ready(env, name: str = "nb") -> bool:
        obj = env.cluster.get("Notebook", name, "ns")
        tpu = obj.get("status", {}).get("tpu", {})
        return tpu.get("readyHosts", 0) == tpu.get("hosts", -1) and tpu.get(
            "sliceHealth"
        ) == "Healthy"

    # -- handlers ----------------------------------------------------------

    def _run_pod_kill(self, doc: dict) -> ExperimentResult:
        params = doc["spec"]["injection"].get("params", {})
        ordinal = int(params.get("podOrdinal", 0))
        env = self.env_factory()
        self._ready_slice(env)
        assert self._slice_ready(env), "steady state never reached"

        env.cluster.delete("Pod", f"nb-{ordinal}", "ns")
        env.manager.run_until_idle()
        recovered = self._slice_ready(env)
        pods = env.cluster.list("Pod", "ns")
        return ExperimentResult(
            doc["metadata"]["name"],
            passed=recovered and len(pods) == 4,
            detail="" if recovered else "slice did not return to Ready",
            observations={"pods_after": len(pods)},
        )

    def _run_placeholder_kill(self, doc: dict) -> ExperimentResult:
        """A warm SlicePool placeholder StatefulSet is deleted out from
        under the pool (node wipe, operator mistake, over-eager GC). The
        level-triggered pool reconcile must regenerate a placeholder — at
        a NEW generation name — and return the pool to all-Ready."""
        from kubeflow_tpu.api import slicepool as sp
        from kubeflow_tpu.api.notebook import TPUSpec
        from kubeflow_tpu.api.slicepool import new_slicepool
        from kubeflow_tpu.k8s import objects as obj_util

        env = self.env_factory()
        env.cluster.create(
            new_slicepool("pool", "ns", TPUSpec("v5e", "4x4"), warm_replicas=1)
        )
        env.manager.run_until_idle()

        def warm():
            return env.cluster.list(
                "StatefulSet", "ns",
                label_selector={sp.STATE_LABEL: sp.STATE_WARM},
            )

        before = warm()
        steady = (
            len(before) == 1
            and env.cluster.get("SlicePool", "pool", "ns")
            .get("status", {}).get("readyReplicas") == 1
        )
        if not steady:
            return ExperimentResult(
                doc["metadata"]["name"], passed=False,
                detail="steady state never reached",
            )

        env.cluster.delete("StatefulSet", obj_util.name_of(before[0]), "ns")
        env.manager.run_until_idle()

        after = warm()
        regenerated = (
            len(after) == 1
            and obj_util.name_of(after[0]) != obj_util.name_of(before[0])
        )
        ready = (
            env.cluster.get("SlicePool", "pool", "ns")
            .get("status", {}).get("readyReplicas") == 1
        )
        return ExperimentResult(
            doc["metadata"]["name"],
            passed=regenerated and ready,
            detail="" if regenerated and ready else (
                f"regenerated={regenerated} ready={ready}"
            ),
            observations={"placeholders_after": len(after)},
        )

    # -- recovery-escalation experiments -----------------------------------

    @staticmethod
    def _recovery_state(env, name: str = "nb") -> dict:
        obj = env.cluster.get("Notebook", name, "ns")
        anns = obj["metadata"].get("annotations", {})
        conds = {
            c.get("type"): c for c in obj.get("status", {}).get("conditions", [])
        }
        tpu = obj.get("status", {}).get("tpu", {})
        return {
            "interrupted": ann.TPU_SLICE_INTERRUPTED in anns,
            "terminal": conds.get("SliceRecoveryFailed", {}).get("status") == "True",
            "healthy": tpu.get("sliceHealth") == "Healthy",
            "duration_stamped": ann.TPU_LAST_INTERRUPTION_DURATION in anns,
        }

    @staticmethod
    def _metric_value(env, metric: str) -> float:
        for line in env.metrics.expose().decode().splitlines():
            if line.startswith(metric + " "):
                return float(line.split()[-1])
        return 0.0

    def _run_preemption_storm(self, doc: dict) -> ExperimentResult:
        """Repeated host kills DURING recovery (a maintenance wave rolling
        through the slice's nodes). Every interruption must still converge
        to SliceRecovered — with the recovery-latency histogram recording
        each — never to a stuck half-recovered state."""
        params = doc["spec"]["injection"].get("params", {})
        kills = int(params.get("kills", 4))
        interval = float(params.get("intervalSeconds", 45))
        env = self.env_factory()
        self._ready_slice(env)
        if not self._slice_ready(env):
            return ExperimentResult(
                doc["metadata"]["name"], passed=False,
                detail="steady state never reached",
            )
        for i in range(kills):
            env.kubelet.preempt_pod(f"nb-{i % 4}", "ns")
            env.manager.tick(interval)
        # Storm over: let every pending requeue fire.
        for _ in range(10):
            env.manager.tick(60.0)
        state = self._recovery_state(env)
        recovered = self._slice_ready(env) and not state["interrupted"]
        recoveries = self._metric_value(env, "tpu_slice_recovery_seconds_count")
        errors = [f"{n}: {e}" for n, _, e in env.manager.reconcile_errors]
        return ExperimentResult(
            doc["metadata"]["name"],
            passed=recovered and recoveries >= 1 and not errors,
            detail=(
                "" if recovered and recoveries >= 1 and not errors else
                f"recovered={recovered} recoveries={recoveries} "
                f"errors={errors[:3]}"
            ),
            observations={"recoveries_recorded": recoveries},
        )

    def _run_capacity_withheld(self, doc: dict) -> ExperimentResult:
        """Replacement capacity never comes back (the preempted host's node
        is gone). With a warm pool: the deadline escalation claims the
        placeholder, freeing its nodes, and the slice recovers. Without:
        escalations exhaust and the state goes terminal SliceRecoveryFailed.
        Either way — convergence with an empty error list and no requeue
        churn, never a silent stall."""
        from kubeflow_tpu.api.notebook import TPUSpec
        from kubeflow_tpu.api.slicepool import new_slicepool

        params = doc["spec"]["injection"].get("params", {})
        warm_pool = bool(params.get("warmPool", False))
        hosts = 8 if warm_pool else 4
        env = self.env_factory(
            node_pools=(("tpu-v5-lite-podslice", "4x4", hosts, 4),)
        )
        if warm_pool:
            env.cluster.create(
                new_slicepool("pool", "ns", TPUSpec("v5e", "4x4"), warm_replicas=1)
            )
            env.manager.run_until_idle()
        self._ready_slice(env)
        if not self._slice_ready(env):
            return ExperimentResult(
                doc["metadata"]["name"], passed=False,
                detail="steady state never reached",
            )

        # Withhold capacity: the host is preempted, THEN its node is
        # reclaimed (spot order: the pod gets its DisruptionTarget first;
        # injecting node-death first would also let the fake kubelet GC the
        # Failed pod before slice-health observes the interruption).
        pod = env.cluster.get("Pod", "nb-2", "ns")
        env.kubelet.preempt_pod("nb-2", "ns")
        env.cluster.delete("Node", pod["spec"]["nodeName"])
        env.manager.run_until_idle()
        # Drive wall-clock through the whole escalation ladder (default
        # config: 300s deadline per phase, 2 escalations, then terminal).
        for _ in range(40):
            env.manager.tick(30.0)

        state = self._recovery_state(env)
        recovered = self._slice_ready(env) and not state["interrupted"]
        converged = recovered if warm_pool else state["terminal"]
        errors = [f"{n}: {e}" for n, _, e in env.manager.reconcile_errors]
        # Churn guard: a converged slice must be quiet — recovered means no
        # recovery requeues at all; terminal requeues only every
        # terminal_requeue_s, so a 2-minute window fires nothing.
        quiet_calls = env.manager.tick(120.0)
        ok = converged and not errors and quiet_calls <= 4
        return ExperimentResult(
            doc["metadata"]["name"],
            passed=ok,
            detail="" if ok else (
                f"recovered={recovered} terminal={state['terminal']} "
                f"quiet_calls={quiet_calls} errors={errors[:3]}"
            ),
            observations={
                "terminal": state["terminal"],
                "recovered": recovered,
                "escalations": self._metric_value(
                    env, "tpu_slice_recovery_escalations_total"
                ),
                "quiet_calls": quiet_calls,
            },
        )

    def _run_apiserver_flap(self, doc: dict) -> ExperimentResult:
        """Apiserver flaps (intermittent write errors) WHILE the escalation
        ladder runs against withheld capacity. Writes fail and retry, but
        the ladder must still converge to the terminal condition once the
        flap ends — the state machine lives in annotations, so a lost write
        is re-derived, never double-counted into a wedged state."""
        from kubeflow_tpu.controller.notebook import NotebookReconciler
        from kubeflow_tpu.controller.preemption import SliceHealthReconciler
        from kubeflow_tpu.k8s.chaos import ChaosClient, FaultConfig
        from kubeflow_tpu.k8s.manager import Manager

        params = doc["spec"]["injection"].get("params", {})
        error_rate = float(params.get("errorRate", 0.3))
        env = self.env_factory()
        # Chaos-wrapped controllers on a dedicated manager (the
        # client-fault pattern): the kubelet stays on the real cluster —
        # the flap hits the controllers, not the node plane.
        chaos = ChaosClient(env.cluster)
        chaos_mgr = Manager(env.cluster, clock=env.clock)
        NotebookReconciler(chaos, clock=env.clock).register(chaos_mgr)
        slice_health = SliceHealthReconciler(chaos, clock=env.clock)
        slice_health.register(chaos_mgr)
        env.kubelet.register(chaos_mgr)

        env.cluster.create(self.notebook_factory(name="nb"))
        chaos_mgr.run_until_idle()
        if not self._slice_ready(env):
            return ExperimentResult(
                doc["metadata"]["name"], passed=False,
                detail="steady state never reached",
            )
        pod = env.cluster.get("Pod", "nb-2", "ns")
        env.kubelet.preempt_pod("nb-2", "ns")
        env.cluster.delete("Node", pod["spec"]["nodeName"])
        chaos_mgr.run_until_idle()

        fault = chaos.add_fault(
            FaultConfig(
                operations=("update", "update_status", "delete"),
                kinds=("Notebook", "StatefulSet"),
                error_rate=error_rate,
            )
        )
        for _ in range(20):
            chaos_mgr.tick(60.0)
        injected = fault.injected_count
        fault.deactivate()
        # Injected errors were the POINT; convergence is judged clean-slate.
        chaos_mgr.reconcile_errors.clear()
        for _ in range(30):
            chaos_mgr.tick(60.0)

        state = self._recovery_state(env)
        recovered = self._slice_ready(env) and not state["interrupted"]
        converged = state["terminal"] or recovered
        errors = [f"{n}: {e}" for n, _, e in chaos_mgr.reconcile_errors]
        quiet_calls = chaos_mgr.tick(120.0)
        ok = converged and not errors and quiet_calls <= 4
        return ExperimentResult(
            doc["metadata"]["name"],
            passed=ok,
            detail="" if ok else (
                f"terminal={state['terminal']} recovered={recovered} "
                f"quiet_calls={quiet_calls} errors={errors[:3]}"
            ),
            observations={"injected": injected, "terminal": state["terminal"]},
        )

    def _run_network_partition(self, doc: dict) -> ExperimentResult:
        params = doc["spec"]["injection"].get("params", {})
        checks = int(params.get("durationChecks", 5))
        env = self.env_factory(culling=True, cull_idle_min=30)
        self._ready_slice(env)

        # Partition: every probe reports unreachable.
        from kubeflow_tpu.controller.culling import HostActivity

        env.prober.activities = [
            HostActivity(host=f"h{i}", reachable=False) for i in range(4)
        ]
        before = (
            env.cluster.get("Notebook", "nb", "ns")["metadata"]
            .get("annotations", {}).get(ann.LAST_ACTIVITY)
        )
        for _ in range(checks):
            env.manager.tick(31 * 60)  # past the idle deadline each time
        obj = env.cluster.get("Notebook", "nb", "ns")
        anns = obj["metadata"].get("annotations", {})
        culled_blind = ann.STOP in anns
        activity_flapped = anns.get(ann.LAST_ACTIVITY) != before

        # Hypothesis clause 2: once the partition heals, culling resumes
        # from real observations — the unreachable window must not have
        # wedged the culler.
        env.prober.activities = [
            HostActivity(host=f"h{i}", reachable=True) for i in range(4)
        ]
        for _ in range(2):
            env.manager.tick(31 * 60)
        healed_anns = (
            env.cluster.get("Notebook", "nb", "ns")["metadata"]
            .get("annotations", {})
        )
        resumed = ann.STOP in healed_anns
        failures = []
        if culled_blind:
            failures.append("culled an unobservable slice")
        if activity_flapped:
            failures.append("last-activity flapped during partition")
        if not resumed:
            failures.append("culling did not resume after heal")
        return ExperimentResult(
            doc["metadata"]["name"],
            passed=not failures,
            detail="; ".join(failures),
            observations={"healed_culled": resumed},
        )

    def _run_controller_outage(self, doc: dict) -> ExperimentResult:
        env = self.env_factory()
        self._ready_slice(env)

        # Outage: mutate without running the manager (events queue up).
        obj = env.cluster.get("Notebook", "nb", "ns")
        obj["metadata"].setdefault("annotations", {})[ann.STOP] = "user-stopped"
        env.cluster.update(obj)
        # Controller comes back: one convergence pass.
        env.manager.run_until_idle()
        sts = env.cluster.get("StatefulSet", "nb", "ns")
        stopped_ok = sts["spec"]["replicas"] == 0

        obj = env.cluster.get("Notebook", "nb", "ns")
        del obj["metadata"]["annotations"][ann.STOP]
        env.cluster.update(obj)
        env.manager.run_until_idle()
        resumed_ok = self._slice_ready(env)
        return ExperimentResult(
            doc["metadata"]["name"],
            passed=stopped_ok and resumed_ok,
            detail=f"stop={'ok' if stopped_ok else 'FAIL'} resume={'ok' if resumed_ok else 'FAIL'}",
        )

    def _run_client_fault(self, doc: dict) -> ExperimentResult:
        from kubeflow_tpu.controller.notebook import NotebookReconciler
        from kubeflow_tpu.k8s.chaos import ChaosClient, FaultConfig
        from kubeflow_tpu.k8s.manager import Manager

        params = doc["spec"]["injection"].get("params", {})
        env = self.env_factory()
        # Rebuild the notebook controller on a chaos-wrapped client, driving
        # it via a dedicated manager (the reference drives Reconcile directly
        # against the chaos client the same way — chaos_test.go:50-152).
        chaos = ChaosClient(env.cluster)
        fault = chaos.add_fault(
            FaultConfig(
                operations=tuple(params.get("operations", ())),
                kinds=tuple(params.get("kinds", ())),
                error_rate=float(params.get("errorRate", 1.0)),
            )
        )
        chaos_mgr = Manager(env.cluster, clock=env.clock)
        NotebookReconciler(chaos, clock=env.clock).register(chaos_mgr)
        env.kubelet.register(chaos_mgr)

        env.cluster.create(self.notebook_factory(name="nb"))
        chaos_mgr.run_until_idle()
        errored = len(chaos_mgr.reconcile_errors) > 0
        no_children = not env.cluster.exists("StatefulSet", "nb", "ns")

        fault.deactivate()
        chaos_mgr.reconcile_errors.clear()
        chaos_mgr.tick(2)  # fire the retry backoff
        sts_ok = env.cluster.exists("StatefulSet", "nb", "ns")
        svc_ok = env.cluster.exists("Service", "nb", "ns")
        return ExperimentResult(
            doc["metadata"]["name"],
            passed=errored and no_children and sts_ok and svc_ok,
            detail=(
                f"errored={errored} no_children={no_children} "
                f"sts={sts_ok} svc={svc_ok}"
            ),
            observations={"injected": fault.injected_count},
        )

    def _run_webhook_error(self, doc: dict) -> ExperimentResult:
        params = doc["spec"]["injection"].get("params", {})
        creates = int(params.get("durationCreates", 3))
        env = self.env_factory(webhooks=True)

        # Disrupt: webhook raises on every admission.
        def broken(req):
            raise RuntimeError("webhook unavailable")

        original = env.cluster._mutating.get("Notebook", [])
        env.cluster._mutating["Notebook"] = [
            type(original[0])(fn=broken, operations=("CREATE", "UPDATE"))
        ]
        failed = 0
        for i in range(creates):
            try:
                env.cluster.create(self.notebook_factory(name=f"nb{i}"))
            except Exception:
                failed += 1
        persisted = sum(
            1 for i in range(creates) if env.cluster.exists("Notebook", f"nb{i}", "ns")
        )

        # Recover and verify fail-closed left nothing half-mutated.
        env.cluster._mutating["Notebook"] = original
        created = env.cluster.create(self.notebook_factory(name="nb-after"))
        lock = created["metadata"]["annotations"].get(ann.STOP)
        return ExperimentResult(
            doc["metadata"]["name"],
            passed=failed == creates and persisted == 0 and lock is not None,
            detail=f"failed={failed}/{creates} persisted={persisted} lock={lock}",
        )

    # -- serving request-lifecycle experiments ------------------------------

    def _run_serving_disconnect_storm(self, doc: dict) -> ExperimentResult:
        """N streaming clients read one token and vanish (notebook tab
        closed). Every slot decoding for a gone client must be reclaimed
        at the engine's next _note_token — zero slots decoding dead work
        — and the cancelled counter must match the storm size exactly."""
        import http.client

        params = doc["spec"]["injection"].get("params", {})
        clients = int(params.get("clients", 4))
        timeout = float(doc["spec"]["recoveryTimeoutSeconds"])
        # Budget far past what decodes before a FIN registers: the
        # requests must still be mid-decode when the broken pipes cancel
        # them, or there is nothing left to reclaim.
        srv = self.serving_factory(max_new_tokens=100).start()
        try:
            conns = []
            for _ in range(clients):
                c = http.client.HTTPConnection(srv.host, srv.port,
                                               timeout=timeout)
                c.request(
                    "POST", "/v1/completions",
                    json.dumps({"prompt": [1, 2, 3], "stream": True}),
                    {"Content-Type": "application/json"},
                )
                conns.append(c)
            for c in conns:
                resp = c.getresponse()
                while True:  # first token, then hang up without warning
                    line = resp.fp.readline()
                    if not line or line.startswith(b"data:"):
                        break
                # Connection: close responses own the socket; closing
                # the response sends FIN mid-stream — the abrupt
                # disconnect under test.
                resp.close()
                c.close()
            busy, cancelled = True, 0
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with srv._lock:
                    busy = (
                        any(r is not None for r in srv.engine._by_slot)
                        or bool(srv.engine._queue)
                        or getattr(srv.engine, "_admitting", None)
                        is not None
                    )
                    cancelled = srv._cancelled
                if not busy and cancelled == clients:
                    break
                time.sleep(0.01)
            healthy = srv._engine_error is None
            passed = not busy and cancelled == clients and healthy
            return ExperimentResult(
                doc["metadata"]["name"],
                passed=passed,
                detail="" if passed else (
                    f"busy={busy} cancelled={cancelled}/{clients} "
                    f"healthy={healthy}"
                ),
                observations={"cancelled": cancelled},
            )
        finally:
            srv.stop()

    def _run_serving_overload(self, doc: dict) -> ExperimentResult:
        """The engine stalls (long compile, slow step) while clients keep
        arriving. Accepted requests park; once the pending queue is full,
        every further arrival must shed with a FAST 429 — the shed path
        takes no engine lock — and complete normally after the stall
        lifts. Shed counter must equal observed 429s exactly.

        The same storm also exercises the SLO burn-rate engine
        (observability/slo.py): request outcomes feed an error-ratio
        objective, and the 100%-shed burst must trip the fast-window
        burn alert mid-storm while an identical engine fed only the
        healthy completions stays silent — the telemetry plane's
        pages-on-overload / silent-when-healthy contract."""
        from kubeflow_tpu.observability.signals import SignalHub
        from kubeflow_tpu.observability.slo import Objective, SLOEngine

        params = doc["spec"]["injection"].get("params", {})
        depth = int(params.get("queueDepth", 3))
        extras = int(params.get("extraClients", 3))
        budget = float(params.get("shedLatencySeconds", 0.5))

        def slo_pair():
            # Windows scaled down to the experiment's seconds-long storm
            # (the production engine uses 60s/300s/1800s); min_events=1
            # because the deterministic burst is this small by design.
            hub = SignalHub(window_s=1.0, windows=64)
            engine = SLOEngine(
                hub,
                (Objective("error_ratio", "ratio", "bad_requests",
                           total_signal="requests", budget=0.05),),
                fast_windows=(5.0, 25.0), slow_window=60.0,
                min_events=1,
            )
            return hub, engine

        storm_hub, storm_slo = slo_pair()
        healthy_hub, healthy_slo = slo_pair()
        srv = self.serving_factory(max_queue_depth=depth, slots=1)
        stall = threading.Event()
        real_step = srv.engine._step

        def stalled_step():
            if not stall.is_set():
                time.sleep(0.005)  # stall: consume nothing, stay alive
                return
            real_step()

        srv.engine._step = stalled_step
        srv.start()
        try:
            accepted: list = []

            def accept_post():
                accepted.append(_serving_post(
                    srv.port, {"prompt": [1, 2, 3], "max_tokens": 2}
                ))

            # Fill deterministically: one request into the slot, then
            # exactly `depth` into the pending queue, each confirmed
            # before the next — no admission race can over/undershoot.
            threads = [threading.Thread(target=accept_post, daemon=True)]
            threads[0].start()
            deadline = time.monotonic() + 30
            while (not any(r is not None for r in srv.engine._by_slot)
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            for i in range(depth):
                t = threading.Thread(target=accept_post, daemon=True)
                t.start()
                threads.append(t)
                while (len(srv.engine._queue) <= i
                       and time.monotonic() < deadline):
                    time.sleep(0.005)

            shed_results = []
            for _ in range(extras):
                t0 = time.monotonic()
                code, _body = _serving_post(
                    srv.port, {"prompt": [1, 2, 3], "max_tokens": 2},
                )
                shed_results.append((code, time.monotonic() - t0))
                # Feed the storm SLO engine at resolution time: a shed
                # is a bad request against the error-ratio objective.
                storm_hub.inc("requests")
                if code != 200:
                    storm_hub.inc("bad_requests")

            # Mid-storm evaluation: every arrival in the fast windows
            # shed, so the error-ratio burn (1.0 / 0.05 = 20) must clear
            # the fast-burn line in BOTH fast windows and page.
            storm_report = storm_slo.evaluate()
            storm_obj = storm_report["objectives"]["error_ratio"]

            stall.set()  # stall lifts; parked work must finish normally
            for t in threads:
                t.join(timeout=60)
            with srv._shed_lock:
                shed_counter = srv._shed
            all_shed = all(code == 429 for code, _ in shed_results)
            slow = [lat for _, lat in shed_results if lat > budget]
            all_done = (
                len(accepted) == depth + 1
                and all(code == 200 for code, _ in accepted)
            )
            # The healthy control sees the same completed traffic minus
            # the storm: zero bad requests, so its engine must NOT page.
            for code, _body in accepted:
                healthy_hub.inc("requests")
                if code != 200:
                    healthy_hub.inc("bad_requests")
            healthy_report = healthy_slo.evaluate()
            healthy_obj = healthy_report["objectives"]["error_ratio"]
            slo_tripped = storm_obj["fast_alert"] and storm_obj["breaching"]
            slo_silent = (not healthy_obj["breaching"]
                          and not healthy_obj["fast_alert"])
            passed = (all_shed and not slow and all_done
                      and shed_counter == extras
                      and slo_tripped and slo_silent)
            return ExperimentResult(
                doc["metadata"]["name"],
                passed=passed,
                detail="" if passed else (
                    f"shed={[c for c, _ in shed_results]} slow={slow} "
                    f"accepted={[c for c, _ in accepted]} "
                    f"counter={shed_counter}/{extras} "
                    f"slo_tripped={slo_tripped} slo_silent={slo_silent}"
                ),
                observations={
                    "shed_counter": shed_counter,
                    "max_shed_latency_s": round(
                        max(lat for _, lat in shed_results), 4
                    ) if shed_results else None,
                    "slo_storm_burn_5s": storm_obj["burn"]["5s"],
                    "slo_storm_breaches": storm_obj["breaches_total"],
                    "slo_healthy_breaches": healthy_obj["breaches_total"],
                },
            )
        finally:
            stall.set()
            srv.stop()

    def _run_serving_engine_stall(self, doc: dict) -> ExperimentResult:
        """The engine thread crashes mid-step (device OOM, preemption).
        Waiters must be aborted with the cause (no hung clients), healthz
        must flip red naming it, and new submits must refuse — loud
        containment, never a silently-dead daemon thread."""
        params = doc["spec"]["injection"].get("params", {})
        cause = str(params.get("cause", "injected engine stall"))
        srv = self.serving_factory()

        def crashing_step():
            raise RuntimeError(cause)

        srv.engine._step = crashing_step
        srv.start()
        try:
            inflight: list = []

            def post():
                inflight.append(_serving_post(
                    srv.port, {"prompt": [1, 2, 3], "max_tokens": 4}
                ))

            t = threading.Thread(target=post, daemon=True)
            t.start()
            t.join(timeout=30)
            aborted_loudly = (
                len(inflight) == 1
                and inflight[0][0] == 500
                and cause in inflight[0][1].get("error", "")
            )
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/healthz", timeout=10
                ) as resp:
                    health_code, health = resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as err:
                health_code, health = err.code, json.loads(err.read())
            health_red = (
                health_code == 503 and cause in health.get("error", "")
            )
            refuse_code, _ = _serving_post(
                srv.port, {"prompt": [1, 2, 3], "max_tokens": 4},
                timeout=10,
            )
            passed = aborted_loudly and health_red and refuse_code == 503
            return ExperimentResult(
                doc["metadata"]["name"],
                passed=passed,
                detail="" if passed else (
                    f"inflight={inflight} health={health_code}:{health} "
                    f"refuse={refuse_code}"
                ),
                observations={"health": health},
            )
        finally:
            srv.stop()

    # -- checkpoint durability handlers ------------------------------------

    @staticmethod
    def _losses(step_fn, state, batches):
        """Drive the trainer, returning (state, [float loss per step]).
        float() synchronizes each step, so the curve is comparable
        bit-for-bit across runs of the same compiled executable."""
        out = []
        for batch in batches:
            state, loss = step_fn(state, batch)
            out.append(float(loss))
        return state, out

    def _checkpoint_resume_result(
        self, doc: dict, workdir: Path, expect_step: int,
        expect_corrupt: int, ref_losses: list,
        extra_ok: bool = True, extra_detail: str = "",
        extra_observations: dict = None,
    ) -> ExperimentResult:
        """The restart half shared by every checkpoint experiment: a FRESH
        manager (new 'process') must restore the newest step that
        VALIDATES — quarantining exactly ``expect_corrupt`` others — and
        training continued from it must reproduce the uninterrupted loss
        curve exactly (checkpointValid + trainingResumed)."""
        from kubeflow_tpu.metrics import Metrics
        from kubeflow_tpu.runtime import checkpoint as ck

        step_fn, fresh_state, batches = self.training_factory()
        metrics = Metrics()
        mgr = ck.CheckpointManager(workdir, max_to_keep=10, metrics=metrics)
        # Restore into a DIFFERENT init (key 7): matching losses below can
        # only come from the checkpoint bytes, not a lucky same-seed init.
        restored, at = mgr.restore_latest(fresh_state(7))
        counted = _counter_value(metrics.checkpoint_corrupt_total)
        quarantined = [
            p.name for p in workdir.iterdir()
            if p.name.startswith(ck.CORRUPT_PREFIX)
        ]
        if at is None:
            resumed_losses = []
        else:
            _, resumed_losses = self._losses(step_fn, restored, batches[at:])
        curve_ok = at == expect_step and resumed_losses == ref_losses[at:]
        passed = (
            curve_ok
            and counted == expect_corrupt
            and len(quarantined) == expect_corrupt
            and extra_ok
        )
        return ExperimentResult(
            doc["metadata"]["name"],
            passed=passed,
            detail="" if passed else (
                f"restored_step={at} (want {expect_step}) "
                f"corrupt_counter={counted} quarantined={quarantined} "
                f"(want {expect_corrupt}) resumed={resumed_losses} "
                f"ref_tail={ref_losses[expect_step:]} {extra_detail}"
            ),
            observations={
                "restored_step": at,
                "quarantined": quarantined,
                "resumed_losses": resumed_losses,
                **(extra_observations or {}),
            },
        )

    def _run_gateway_replica_kill(self, doc: dict) -> ExperimentResult:
        """A replica pod dies abruptly with streams in flight. The
        gateway must (a) terminate exactly the severed streams with a
        distinguishable error event — every stream still ends in [DONE],
        silent truncation is the one outcome forbidden; (b) heal the
        ring to the survivor within the recovery window; (c) serve
        post-heal traffic with zero further failures."""
        import http.client

        from kubeflow_tpu.models.gateway import ServingGateway

        params = doc["spec"]["injection"].get("params", {})
        streams = int(params.get("streams", 3))
        timeout = float(doc["spec"]["recoveryTimeoutSeconds"])
        replicas = [_CrashableReplica().start() for _ in range(2)]
        gw = ServingGateway(
            [r.endpoint for r in replicas], port=0, block_size=4,
            health_interval_s=0.1, reroute_budget=2,
        ).start()
        collected: list = [[] for _ in range(streams)]

        def reader(i: int) -> None:
            conn = http.client.HTTPConnection(gw.host, gw.port,
                                              timeout=timeout)
            try:
                conn.request(
                    "POST", "/v1/completions",
                    json.dumps({"prompt": [10 * i + j for j in range(8)],
                                "stream": True}).encode(),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                while True:
                    line = resp.fp.readline()
                    if not line:
                        break
                    if line.startswith(b"data:"):
                        collected[i].append(line)
                    if line == b"data: [DONE]\n":
                        break
            finally:
                conn.close()

        try:
            threads = [
                threading.Thread(target=reader, args=(i,), daemon=True)
                for i in range(streams)
            ]
            for t in threads:
                t.start()
            # Every stream must be past its first token before the kill,
            # or there is nothing mid-stream to sever.
            deadline = time.monotonic() + timeout
            while (any(not lines for lines in collected)
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            victim = max(replicas, key=lambda r: r.inflight)
            survivor = next(r for r in replicas if r is not victim)
            burst = victim.crash()
            for t in threads:
                t.join(timeout=timeout)
            # Bounded error burst, no silent truncation: every stream
            # terminated with [DONE]; exactly the severed ones carry the
            # mid-stream error event.
            terminated = sum(
                lines[-1] == b"data: [DONE]\n" for lines in collected
            )
            errored = sum(
                any(b"replica lost mid-stream" in ln for ln in lines)
                for lines in collected
            )
            # Correlation survives the loss: by the time a stream dies
            # the response headers are long gone, so the SSE error event
            # itself must carry the request id — it is the only handle
            # left for joining the truncated stream against gateway logs
            # and the trace export.
            error_events = [
                ln for lines in collected for ln in lines
                if b"replica lost mid-stream" in ln
            ]
            correlated = all(
                b'"request_id"' in ln for ln in error_events
            )
            # Ring heals to the survivor alone.
            healed = False
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if gw.ring_nodes() == frozenset({survivor.endpoint}):
                    healed = True
                    break
                time.sleep(0.02)
            # Throughput recovers: post-heal traffic all succeeds and
            # the failed count never grows past the burst.
            failed_before = gw.stats()["failed"]
            recovered = 0
            for i in range(4):
                code, _ = _serving_post(
                    gw.port, {"prompt": [99, i], "stream": False},
                    timeout=timeout,
                )
                recovered += code == 200
            stats = gw.stats()
            passed = (
                burst >= 1
                and terminated == streams
                and errored == burst
                and correlated
                and healed
                and recovered == 4
                and stats["failed"] == failed_before == burst
            )
            return ExperimentResult(
                doc["metadata"]["name"],
                passed=passed,
                detail="" if passed else (
                    f"burst={burst} terminated={terminated}/{streams} "
                    f"errored={errored} correlated={correlated} "
                    f"healed={healed} "
                    f"recovered={recovered}/4 failed={stats['failed']}"
                ),
                observations={
                    "error_burst": burst,
                    "errored_streams": errored,
                    "correlated_errors": correlated,
                    "reroutes": stats["reroutes"],
                    "healed": healed,
                },
            )
        finally:
            gw.stop()
            for r in replicas:
                r.stop()

    def _run_serving_kv_handoff_loss(self, doc: dict) -> ExperimentResult:
        """The prefill-tier replica dies mid-KV-export with a handoff in
        flight. The gateway must (a) re-route the in-flight request to
        the surviving prefill replica within the re-route budget — the
        client stream still delivers every token and ends in [DONE],
        with silent truncation the one forbidden outcome; (b) drop the
        dead replica from the ring within the recovery window; (c) keep
        the decode tier healthy throughout: post-heal requests all
        stream through the paged-KV handoff with zero transfer
        failures."""
        import http.client

        from kubeflow_tpu.models.gateway import ServingGateway

        params = doc["spec"]["injection"].get("params", {})
        decode_tokens = int(params.get("decodeTokens", 5))
        post_heal = int(params.get("postHealRequests", 3))
        timeout = float(doc["spec"]["recoveryTimeoutSeconds"])

        victim = _CrashablePrefill().start()
        prefill = _paged_serving_factory(tier_role="prefill").start()
        decode = _paged_serving_factory(tier_role="decode").start()
        p_ep = f"{prefill.host}:{prefill.port}"
        d_ep = f"{decode.host}:{decode.port}"
        gw = ServingGateway(
            [victim.endpoint, p_ep, d_ep], port=0, block_size=8,
            health_interval_s=0.1, reroute_budget=2, tier_mode="disagg",
            tier_roles={victim.endpoint: "prefill", p_ep: "prefill",
                        d_ep: "decode"},
        ).start()

        def stream(prompt):
            """(sse_lines, tokens) for one streamed completion."""
            conn = http.client.HTTPConnection(gw.host, gw.port,
                                              timeout=timeout)
            lines, toks = [], []
            try:
                conn.request(
                    "POST", "/v1/completions",
                    json.dumps({"prompt": prompt, "stream": True,
                                "max_tokens": decode_tokens}).encode(),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                while True:
                    line = resp.fp.readline()
                    if not line:
                        break
                    if line.startswith(b"data:"):
                        lines.append(line)
                    if line == b"data: [DONE]\n":
                        break
                for ln in lines:
                    if ln == b"data: [DONE]\n":
                        continue
                    body = json.loads(ln[5:])
                    if "token" in body:
                        toks.append(body["token"])
            finally:
                conn.close()
            return lines, toks

        try:
            # All three replicas must be in the ring before the kill
            # has a ring to matter in.
            deadline = time.monotonic() + timeout
            while (len(gw.ring_nodes()) < 3
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            # A prompt whose prefill walk starts at the victim: the
            # in-flight handoff must land on the pod that dies, not on
            # whichever replica the ring happens to prefer.
            prompt = None
            for nonce in range(3, 250):
                cand = [nonce, 5, 7, 11, 13, 17, 19, 23, 29, 31]
                walk = gw._tier_candidates(
                    "prefill", gw._route_key(cand)
                )
                if walk and walk[0] == victim.endpoint:
                    prompt = cand
                    break
            if prompt is None:
                return ExperimentResult(
                    doc["metadata"]["name"], passed=False,
                    detail="no prompt routed to the victim replica",
                )
            sev_lines, sev_toks = stream(prompt)
            mid = gw.stats()
            rerouted = (
                victim.hits >= 1
                and bool(sev_lines)
                and sev_lines[-1] == b"data: [DONE]\n"
                and len(sev_toks) == decode_tokens
                and not any(b'"error"' in ln for ln in sev_lines)
                and mid["reroutes"] >= 1
                and mid["kv_transfers"] == 1
            )
            # Ring heals: the dead prefill pod leaves within the window.
            healed = False
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if gw.ring_nodes() == frozenset({p_ep, d_ep}):
                    healed = True
                    break
                time.sleep(0.02)
            # Decode tier stayed healthy: post-heal traffic still
            # streams through the handoff, every import lands on the
            # decode replica, and no transfer ever failed.
            completed = 0
            for i in range(post_heal):
                lines, toks = stream(
                    [40 + i, 41, 42, 43, 44, 45, 46, 47, 48, 49]
                )
                completed += (bool(lines)
                              and lines[-1] == b"data: [DONE]\n"
                              and len(toks) == decode_tokens)
            code, _ = _serving_get(decode.port, "/healthz",
                                   timeout=timeout)
            _, dstats = _serving_get(decode.port, "/stats",
                                     timeout=timeout)
            stats = gw.stats()
            decode_ok = (
                code == 200
                and completed == post_heal
                and stats["kv_transfers"] == 1 + post_heal
                and stats["kv_transfer_failures"] == 0
                and dstats.get("kv_handoff", {}).get("imports")
                == 1 + post_heal
            )
            passed = rerouted and healed and decode_ok
            return ExperimentResult(
                doc["metadata"]["name"],
                passed=passed,
                detail="" if passed else (
                    f"rerouted={rerouted} (hits={victim.hits} "
                    f"toks={len(sev_toks)}/{decode_tokens} "
                    f"reroutes={mid['reroutes']}) healed={healed} "
                    f"decode_ok={decode_ok} "
                    f"(completed={completed}/{post_heal} "
                    f"transfers={stats['kv_transfers']} "
                    f"transfer_failures={stats['kv_transfer_failures']})"
                ),
                observations={
                    "victim_hits": victim.hits,
                    "reroutes": stats["reroutes"],
                    "kv_transfers": stats["kv_transfers"],
                    "kv_transfer_failures":
                        stats["kv_transfer_failures"],
                    "healed": healed,
                },
            )
        finally:
            gw.stop()
            victim.stop()
            prefill.stop()
            decode.stop()

    def _run_serving_kv_peer_loss(self, doc: dict) -> ExperimentResult:
        """The peer that won the /kv/probe auction dies mid-/kv/chain
        export: the gateway holds a torn payload with the client stream
        already open. The fetch ladder must fall through to a plain
        re-prefill on the routed replica (every token + [DONE], zero
        error events), negative-cache the corpse so it is not re-probed,
        and the health loop must drop it from the ring; post-heal
        traffic keeps serving with zero new fetch failures."""
        import http.client

        from kubeflow_tpu.models.gateway import ServingGateway

        params = doc["spec"]["injection"].get("params", {})
        decode_tokens = int(params.get("decodeTokens", 5))
        post_heal = int(params.get("postHealRequests", 3))
        timeout = float(doc["spec"]["recoveryTimeoutSeconds"])

        victim = _CrashablePeer().start()
        replica = _paged_serving_factory(tier_role="fused").start()
        r_ep = f"{replica.host}:{replica.port}"
        gw = ServingGateway(
            [victim.endpoint, r_ep], port=0, block_size=8,
            health_interval_s=0.1, kv_peer_fanout=2,
        ).start()

        def stream(prompt):
            """(sse_lines, tokens) for one streamed completion."""
            conn = http.client.HTTPConnection(gw.host, gw.port,
                                              timeout=timeout)
            lines, toks = [], []
            try:
                conn.request(
                    "POST", "/v1/completions",
                    json.dumps({"prompt": prompt, "stream": True,
                                "max_tokens": decode_tokens}).encode(),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                while True:
                    line = resp.fp.readline()
                    if not line:
                        break
                    if line.startswith(b"data:"):
                        lines.append(line)
                    if line == b"data: [DONE]\n":
                        break
                for ln in lines:
                    if ln == b"data: [DONE]\n":
                        continue
                    body = json.loads(ln[5:])
                    if "token" in body:
                        toks.append(body["token"])
            finally:
                conn.close()
            return lines, toks

        try:
            deadline = time.monotonic() + timeout
            while (len(gw.ring_nodes()) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            # A prompt the fused walk routes to the REAL replica — the
            # victim must be a probed peer, not the route target. The
            # prefix router learns a chain on first sight, so warm it
            # once and target with the stable key the request recomputes.
            prompt = None
            for nonce in range(3, 250):
                cand = [nonce, 5, 7, 11, 13, 17, 19, 23, 29, 31]
                gw._route_key(cand)
                walk = gw._candidates(gw._route_key(cand))
                if walk and walk[0] == r_ep:
                    prompt = cand
                    break
            if prompt is None:
                return ExperimentResult(
                    doc["metadata"]["name"], passed=False,
                    detail="no prompt routed to the real replica",
                )
            sev_lines, sev_toks = stream(prompt)
            mid = gw.stats()
            degraded = (
                victim.probe_hits >= 1
                and victim.chain_hits == 1
                and bool(sev_lines)
                and sev_lines[-1] == b"data: [DONE]\n"
                and len(sev_toks) == decode_tokens
                and not any(b'"error"' in ln for ln in sev_lines)
                and mid["kv_peer_fetches"] == 0
                and mid["kv_peer_fetch_failures"] >= 1
                and victim.endpoint in mid["kv_peer"]["negative_cached"]
            )
            # Ring heals: the dead peer leaves within the window.
            healed = False
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if gw.ring_nodes() == frozenset({r_ep}):
                    healed = True
                    break
                time.sleep(0.02)
            # Post-heal: fresh prompts keep streaming; a peerless walk
            # is a clean no-peer-chain, never a counted failure.
            completed = 0
            for i in range(post_heal):
                lines, toks = stream(
                    [80 + i, 81, 82, 83, 84, 85, 86, 87, 88, 89]
                )
                completed += (bool(lines)
                              and lines[-1] == b"data: [DONE]\n"
                              and len(toks) == decode_tokens)
            stats = gw.stats()
            post_ok = (
                completed == post_heal
                and stats["kv_peer_fetch_failures"]
                == mid["kv_peer_fetch_failures"]
            )
            passed = degraded and healed and post_ok
            return ExperimentResult(
                doc["metadata"]["name"],
                passed=passed,
                detail="" if passed else (
                    f"degraded={degraded} (probes={victim.probe_hits} "
                    f"pulls={victim.chain_hits} "
                    f"toks={len(sev_toks)}/{decode_tokens} "
                    f"fetches={mid['kv_peer_fetches']} "
                    f"failures={mid['kv_peer_fetch_failures']} "
                    f"negative={mid['kv_peer']['negative_cached']}) "
                    f"healed={healed} post_ok={post_ok} "
                    f"(completed={completed}/{post_heal})"
                ),
                observations={
                    "victim_probe_hits": victim.probe_hits,
                    "victim_chain_hits": victim.chain_hits,
                    "kv_peer_fetch_failures":
                        stats["kv_peer_fetch_failures"],
                    "negative_cached":
                        list(mid["kv_peer"]["negative_cached"]),
                    "healed": healed,
                },
            )
        finally:
            gw.stop()
            victim.stop()
            replica.stop()

    def _run_checkpoint_kill_mid_save(self, doc: dict) -> ExperimentResult:
        """SIGKILL lands mid-save: the IO layer dies between file writes
        (save() contains only OSError, so _SimulatedCrash abandons the
        staging dir exactly as a dead process would). The previously
        committed step must stay the restorable latest — the torn staging
        dir is invisible to restore — and the resumed loss curve must
        match the uninterrupted run's exactly."""
        import shutil
        import tempfile

        from kubeflow_tpu.runtime import checkpoint as ck

        params = doc["spec"]["injection"].get("params", {})
        kill_step = int(params.get("killAtStep", 3))
        files_before_kill = int(params.get("filesBeforeKill", 2))
        step_fn, fresh_state, batches = self.training_factory()
        _, ref_losses = self._losses(step_fn, fresh_state(0), batches)

        class KillerIO(ck.CheckpointIO):
            armed = False
            writes = 0

            def write_file(self, path, data):
                if self.armed:
                    self.writes += 1
                    if self.writes > files_before_kill:
                        raise _SimulatedCrash(f"died writing {path.name}")
                super().write_file(path, data)

        workdir = Path(tempfile.mkdtemp(prefix="chaos-ckpt-kill-"))
        try:
            io = KillerIO()
            mgr = ck.CheckpointManager(workdir, max_to_keep=10, io=io)
            state = fresh_state(0)
            crashed = False
            for i, batch in enumerate(batches):
                state, _ = step_fn(state, batch)
                if i + 1 == kill_step:
                    io.armed = True
                try:
                    mgr.save(i + 1, state)
                except _SimulatedCrash:
                    crashed = True
                    break
            torn = [
                p.name for p in workdir.iterdir()
                if p.name.startswith(".tmp-")
            ]
            return self._checkpoint_resume_result(
                doc, workdir,
                expect_step=kill_step - 1, expect_corrupt=0,
                ref_losses=ref_losses,
                # The injection must actually have fired AND left a torn
                # staging dir, or the hypothesis never ran.
                extra_ok=crashed and bool(torn),
                extra_detail=f"crashed={crashed} torn={torn}",
                extra_observations={"torn_staging_dirs": torn},
            )
        finally:
            shutil.rmtree(workdir, ignore_errors=True)

    def _run_checkpoint_restore_corrupt(self, doc: dict) -> ExperimentResult:
        """Bit-rot or truncation on the newest committed step. Restore
        must catch it against the manifest (CRC32 / size), quarantine the
        step as corrupt-<step>-* with tpu_checkpoint_corrupt_total
        incremented, fall back to the previous valid step, and resume with
        zero loss-curve divergence."""
        import shutil
        import tempfile

        from kubeflow_tpu.runtime import checkpoint as ck

        params = doc["spec"]["injection"].get("params", {})
        mode = str(params.get("corruption", "bitflip"))
        step_fn, fresh_state, batches = self.training_factory()
        _, ref_losses = self._losses(step_fn, fresh_state(0), batches)
        workdir = Path(tempfile.mkdtemp(prefix="chaos-ckpt-rot-"))
        try:
            mgr = ck.CheckpointManager(workdir, max_to_keep=10)
            state = fresh_state(0)
            for i, batch in enumerate(batches):
                state, _ = step_fn(state, batch)
                mgr.save(i + 1, state)
            newest = workdir / str(len(batches))
            victim = sorted(newest.glob("*.bin"))[0]
            blob = bytearray(victim.read_bytes())
            if mode == "truncate":
                victim.write_bytes(bytes(blob[:-8]))
            else:
                blob[len(blob) // 2] ^= 0xFF
                victim.write_bytes(bytes(blob))
            return self._checkpoint_resume_result(
                doc, workdir,
                expect_step=len(batches) - 1, expect_corrupt=1,
                ref_losses=ref_losses,
                extra_detail=f"corruption={mode} victim={victim.name}",
            )
        finally:
            shutil.rmtree(workdir, ignore_errors=True)

    def _run_checkpoint_disk_full(self, doc: dict) -> ExperimentResult:
        """The checkpoint volume fills mid-training (ENOSPC from the IO
        layer). Saves must FAIL CLEANLY — counted, staging dirs removed,
        training uninterrupted, last good step still restorable — and once
        space returns the very next (emergency) save must commit: failure
        history must not wedge the manager."""
        import errno
        import shutil
        import tempfile

        from kubeflow_tpu.runtime import checkpoint as ck

        params = doc["spec"]["injection"].get("params", {})
        full_from = int(params.get("fullFromStep", 3))
        step_fn, fresh_state, batches = self.training_factory()
        _, ref_losses = self._losses(step_fn, fresh_state(0), batches)
        workdir = Path(tempfile.mkdtemp(prefix="chaos-ckpt-enospc-"))
        try:

            class FullDiskIO(ck.CheckpointIO):
                full = False

                def write_file(self, path, data):
                    if self.full:
                        raise OSError(errno.ENOSPC, "No space left on device")
                    super().write_file(path, data)

            io = FullDiskIO()
            mgr = ck.CheckpointManager(workdir, max_to_keep=10, io=io)
            state = fresh_state(0)
            for i, batch in enumerate(batches):
                state, _ = step_fn(state, batch)
                if i + 1 == full_from:
                    io.full = True
                mgr.save(i + 1, state)
            failures = mgr.save_failures
            stray = [
                p.name for p in workdir.iterdir()
                if p.name.startswith(".tmp-")
            ]
            result = self._checkpoint_resume_result(
                doc, workdir,
                expect_step=full_from - 1, expect_corrupt=0,
                ref_losses=ref_losses,
                extra_ok=(
                    failures == len(batches) - full_from + 1 and not stray
                ),
                extra_detail=f"save_failures={failures} stray_tmp={stray}",
                extra_observations={"save_failures": failures},
            )
            # Space comes back: the manager's emergency path must flush the
            # newest pending state on the first try.
            io.full = False
            recovered = (
                mgr.emergency_save() and mgr.latest_step() == len(batches)
            )
            if not recovered:
                return ExperimentResult(
                    doc["metadata"]["name"], passed=False,
                    detail=(
                        "save did not recover after ENOSPC lifted "
                        f"(latest={mgr.latest_step()}); prior: "
                        f"{result.detail or 'resume ok'}"
                    ),
                    observations=result.observations,
                )
            return result
        finally:
            shutil.rmtree(workdir, ignore_errors=True)

    def _run_autoscaler_scaledown_storm(self, doc: dict) -> ExperimentResult:
        """Scale-down under stream churn. Slow streams run across a
        3-replica fleet while the autoscaler — fed real telemetry, fast
        probe cadence — sees ebb and drains replicas toward
        min_replicas, with a second request wave landing mid-drain. The
        promise under test: the drained replica leaves the ring at the
        decision instant yet its in-flight streams all run to [DONE];
        its slice is released only once it is empty (zero connections
        severed at release); no stream errors, nothing is shed."""
        import http.client

        from kubeflow_tpu.models.autoscaler import AutoscalerConfig
        from kubeflow_tpu.models.gateway import ServingGateway
        from kubeflow_tpu.observability.signals import (
            FleetTelemetry,
            SignalsConfig,
        )

        params = doc["spec"]["injection"].get("params", {})
        streams = int(params.get("streams", 6))
        churn = int(params.get("churnStreams", 4))
        replica_count = int(params.get("replicas", 3))
        timeout = float(doc["spec"]["recoveryTimeoutSeconds"])

        replicas = [
            _DrainableReplica(tokens=30, token_delay_s=0.05).start()
            for _ in range(replica_count)
        ]
        by_ep = {r.endpoint: r for r in replicas}

        class _Prov:
            # In-process provisioner: the "slice" is the fake replica.
            def scale_up(self, tier, now=None):
                return None  # the storm only exercises the down path

            def drain(self, ep):
                by_ep[ep].drain()

            def drained(self, ep):
                return by_ep[ep].drained

            def release(self, ep):
                by_ep[ep].release()

        telemetry = FleetTelemetry(SignalsConfig(window_s=0.5, windows=60))
        gw = ServingGateway(
            [r.endpoint for r in replicas], port=0, block_size=4,
            health_interval_s=0.05, reroute_budget=2,
            telemetry=telemetry,
            autoscaler_config=AutoscalerConfig(
                min_replicas=1, max_replicas=replica_count,
                down_consecutive=2, down_cooldown_s=0.2,
                up_cooldown_s=0.2, max_actions_per_window=8,
                actions_window_s=30.0, drain_budget_s=timeout,
                stale_after_s=5.0,
            ),
            autoscaler_provisioner=_Prov(),
        ).start()
        collected: list = [[] for _ in range(streams + churn)]

        def reader(i: int) -> None:
            conn = http.client.HTTPConnection(gw.host, gw.port,
                                              timeout=timeout)
            try:
                conn.request(
                    "POST", "/v1/completions",
                    json.dumps({"prompt": [10 * i + j for j in range(8)],
                                "stream": True,
                                "user": f"tenant-{i % 3}"}).encode(),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                while True:
                    line = resp.fp.readline()
                    if not line:
                        break
                    if line.startswith(b"data:"):
                        collected[i].append(line)
                    if line == b"data: [DONE]\n":
                        break
            finally:
                conn.close()

        try:
            threads = [
                threading.Thread(target=reader, args=(i,), daemon=True)
                for i in range(streams)
            ]
            for t in threads:
                t.start()
            # Every first-wave stream is mid-flight before any drain.
            deadline = time.monotonic() + timeout
            while (any(not lines for lines in collected[:streams])
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            # Ebb under churn: wait for the first scale-down, then land
            # a second wave while the victim is still draining.
            scale_downs = 0
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                scale_downs = gw.stats()["autoscaler"]["scale_downs"]
                if scale_downs:
                    break
                time.sleep(0.02)
            churn_threads = [
                threading.Thread(target=reader, args=(streams + i,),
                                 daemon=True)
                for i in range(churn)
            ]
            for t in churn_threads:
                t.start()
            for t in threads + churn_threads:
                t.join(timeout=timeout)
            # Drains settle: every initiated drain released its slice.
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                scaler = gw.stats()["autoscaler"]
                if not scaler["draining"]:
                    break
                time.sleep(0.02)
            scaler = gw.stats()["autoscaler"]
            stats = gw.stats()
            decisions = gw.autoscaler.debug()["decisions"]
            releases = [d for d in decisions if d["action"] == "release"]
            released = [r for r in replicas if r.severed_at_release >= 0]
            terminated = sum(
                lines and lines[-1] == b"data: [DONE]\n"
                for lines in collected
            )
            errored = sum(
                any(b'"error"' in ln for ln in lines)
                for lines in collected
            )
            severed = sum(r.severed_at_release for r in released)
            budget_blown = sum(
                "exceeded" in "; ".join(d["reasons"]) for d in releases
            )
            passed = (
                scaler["scale_downs"] >= 1
                and len(releases) == len(released) >= 1
                and severed == 0
                and budget_blown == 0
                and terminated == streams + churn
                and errored == 0
                and stats["shed"] == 0
                and stats["failed"] == 0
                and all(r.endpoint not in gw.replica_endpoints()
                        for r in released)
            )
            return ExperimentResult(
                doc["metadata"]["name"],
                passed=passed,
                detail="" if passed else (
                    f"scale_downs={scaler['scale_downs']} "
                    f"releases={len(releases)}/{len(released)} "
                    f"severed_at_release={severed} "
                    f"budget_blown={budget_blown} "
                    f"terminated={terminated}/{streams + churn} "
                    f"errored={errored} shed={stats['shed']} "
                    f"failed={stats['failed']}"
                ),
                observations={
                    "scale_downs": scaler["scale_downs"],
                    "releases": len(releases),
                    "severed_at_release": severed,
                    "terminated_streams": terminated,
                    "shed": stats["shed"],
                },
            )
        finally:
            gw.stop()
            for r in replicas:
                r.stop()

    # -- live migration handler --------------------------------------------

    def _run_migration_storm(self, doc: dict) -> ExperimentResult:
        """Repeated preemption notices against a LIVE tiny trainer, each
        one driving a full proactive migration (runtime/migration.py):
        emergency-save -> warm-slice claim -> restore -> routing flip.
        Throughput may dip between segments but never zeroes; every
        migration must resume token/loss-exact against the uninterrupted
        reference curve (same zero-divergence oracle as the checkpoint
        experiments); and each migration must leave ONE complete
        ``migration`` trace with a child span per pipeline step."""
        import shutil
        import tempfile

        from kubeflow_tpu.observability import tracing
        from kubeflow_tpu.runtime import checkpoint as ck
        from kubeflow_tpu.runtime.migration import (
            MIGRATION_STEPS,
            MigrationConfig,
            MigrationOrchestrator,
        )

        params = doc["spec"]["injection"].get("params", {})
        migrations = int(params.get("migrations", 2))
        steps_between = int(params.get("stepsBetween", 1))

        step_fn, fresh_state, batches = self.training_factory()
        # Uninterrupted reference run: the zero-divergence oracle every
        # post-migration segment is held to, batch index by batch index.
        _, ref_losses = self._losses(step_fn, fresh_state(0), batches)

        workdir = Path(tempfile.mkdtemp(prefix="chaos-migration-storm-"))
        exporter = tracing.InMemoryExporter()
        tracing.set_tracer_provider(tracing.TracerProvider(exporter=exporter))
        try:
            # The "live trainer": cursor counts batches consumed; every
            # step commits synchronously with the start_batch cursor in
            # metadata (the train_with_checkpointing convention), so an
            # emergency save always has a fresh commit to skip to.
            live = {
                "mgr": ck.CheckpointManager(workdir, max_to_keep=10),
                "state": fresh_state(0),
                "cursor": 0,
            }
            trained: list = []  # (batch index, float loss)

            def train(n_steps: int) -> int:
                done = 0
                while done < n_steps and live["cursor"] < len(batches):
                    i = live["cursor"]
                    live["state"], loss = step_fn(live["state"], batches[i])
                    live["cursor"] = i + 1
                    live["mgr"].save(
                        live["cursor"], live["state"],
                        metadata={"start_batch": live["cursor"]},
                    )
                    trained.append((i, float(loss)))
                    done += 1
                return done

            class _LiveCheckpoint:
                """The orchestrator holds ONE checkpoint handle, but the
                live manager changes identity on every restore (each
                restore is a new 'process'); delegate per call."""

                @staticmethod
                def last_commit_age():
                    return live["mgr"].last_commit_age()

                @staticmethod
                def latest_step():
                    return live["mgr"].latest_step()

                @staticmethod
                def emergency_save(grace_s=None):
                    return live["mgr"].emergency_save(grace_s=grace_s)

            warm = [f"warm-{i}" for i in range(migrations)]
            claimed: list = []
            routing = {"active": "slice-0", "drained": []}

            def claim_fn(claimant, deadline):
                if not warm:
                    return None
                pool = warm.pop(0)
                claimed.append((claimant, pool))
                return pool

            def restore_fn(deadline):
                # A fresh manager on the warm slice ("new process"),
                # restoring into a DIFFERENT init (key 7): matching
                # losses afterwards can only come from checkpoint bytes.
                mgr2 = ck.CheckpointManager(workdir, max_to_keep=10)
                restored, at = mgr2.restore_latest(fresh_state(7))
                if at is None:
                    return None
                live["mgr"] = mgr2
                live["state"] = restored
                live["cursor"] = ck.resume_start_batch(mgr2, at)
                return {"step": at, "start_batch": live["cursor"]}

            def flip_fn(deadline):
                if not claimed:
                    return False
                routing["drained"].append(routing["active"])
                routing["active"] = claimed[-1][1]
                return True

            fallbacks: list = []
            orch = MigrationOrchestrator(
                # fresh_within_s=0 so every migration exercises the real
                # emergency-save path (its internal skip-if-fresh still
                # applies when the last step already committed).
                MigrationConfig(fresh_within_s=0.0),
                checkpoint=_LiveCheckpoint(),
                claim_fn=claim_fn,
                restore_fn=restore_fn,
                flip_fn=flip_fn,
                fallback_fn=lambda step, reason: fallbacks.append(
                    (step, reason)),
            )

            reports = []
            segments = []
            for _ in range(migrations):
                segments.append(train(steps_between))
                reports.append(orch.migrate("preemption-notice"))
            # Final segment drains the remaining batches on the last
            # warm slice — proof the flip left a trainable replica.
            segments.append(train(len(batches) - live["cursor"]))

            roots = exporter.by_name("migration")
            want_children = sorted(f"migration.{s}" for s in MIGRATION_STEPS)
            complete_traces = sum(
                root.attributes.get("completed") is True
                and sorted(
                    s.name for s in exporter.spans
                    if s.parent_id == root.span_id
                ) == want_children
                for root in roots
            )

            exact = all(loss == ref_losses[i] for i, loss in trained)
            throughput_ok = (
                all(s >= 1 for s in segments)
                and live["cursor"] == len(batches)
            )
            stats = orch.stats()
            passed = (
                all(r.completed for r in reports)
                and not fallbacks
                and exact
                and throughput_ok
                and complete_traces == len(roots) == migrations
                and len(claimed) == migrations and not warm
                and routing["active"] == f"warm-{migrations - 1}"
                and stats["migrations_completed"] == migrations
                and stats["migrations_fell_back"] == 0
            )
            return ExperimentResult(
                doc["metadata"]["name"],
                passed=passed,
                detail="" if passed else (
                    f"completed={[r.completed for r in reports]} "
                    f"fallbacks={fallbacks} exact={exact} "
                    f"segments={segments} cursor={live['cursor']}/"
                    f"{len(batches)} traces={complete_traces}/{len(roots)} "
                    f"(want {migrations}) claimed={claimed} "
                    f"routing={routing} stats={stats}"
                ),
                observations={
                    "migrations": migrations,
                    "segments": segments,
                    "restored_steps": [r.restored_step for r in reports],
                    "trained_losses": [loss for _, loss in trained],
                    "complete_traces": complete_traces,
                    "active_replica": routing["active"],
                },
            )
        finally:
            tracing.set_tracer_provider(tracing.TracerProvider())
            shutil.rmtree(workdir, ignore_errors=True)
