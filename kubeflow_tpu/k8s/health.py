"""healthz / readyz probe endpoints.

Reference parity: both managers wire named checks into controller-runtime's
healthz server (reference components/notebook-controller/main.go:125-133
``AddHealthzCheck("healthz", healthz.Ping)`` / ``AddReadyzCheck``; ODH
main.go registers the same pair). ``HealthChecks`` is the registry;
``HealthServer`` optionally serves it over real HTTP (the probe-addr flag)
for e2e runs.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


def ping() -> None:
    """healthz.Ping analog: always healthy."""


class ServeWatchdog:
    """Readyz check that the serve loop is actually draining.

    ``manager.reconcile_errors`` catches reconcilers that run and fail;
    what it can NOT catch is a drain loop that stopped running at all — a
    reconcile blocked forever in a hung client call, a deadlocked watch
    stream, a loop crashed outside the per-cycle try. The serve loop calls
    ``beat(manager.cursor)`` after every successful cycle; readyz turns
    unready once no beat has landed within ``window_s``, so Kubernetes
    restarts a wedged controller instead of routing to a zombie.
    """

    def __init__(
        self,
        window_s: float = 60.0,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.window_s = window_s
        # Monotonic by default: a wall-clock step (NTP, suspend) must not
        # fake a stall or mask a real one.
        self._clock = clock or time.monotonic
        self._last_beat: Optional[float] = None
        self.last_cursor: Optional[int] = None

    def beat(self, cursor: int) -> None:
        """Record one completed drain cycle (cursor = manager.cursor)."""
        self.last_cursor = cursor
        self._last_beat = self._clock()

    def check(self) -> None:
        if self._last_beat is None:
            raise RuntimeError("serve loop has not completed a cycle yet")
        age = self._clock() - self._last_beat
        if age > self.window_s:
            raise RuntimeError(
                f"serve loop stalled: no heartbeat for {age:.0f}s "
                f"(window {self.window_s:.0f}s, last cursor "
                f"{self.last_cursor})"
            )

    def register(self, checks: "HealthChecks", name: str = "serve-loop") -> None:
        checks.add_readyz_check(name, self.check)


class HealthChecks:
    """Named check registry; a check passes unless it raises."""

    def __init__(self):
        self._healthz: dict[str, Callable[[], None]] = {}
        self._readyz: dict[str, Callable[[], None]] = {}

    def add_healthz_check(self, name: str, fn: Callable[[], None]) -> None:
        self._healthz[name] = fn

    def add_readyz_check(self, name: str, fn: Callable[[], None]) -> None:
        self._readyz[name] = fn

    def _run(self, checks: dict) -> tuple[bool, dict]:
        detail = {}
        ok = True
        for name, fn in checks.items():
            try:
                fn()
                detail[name] = "ok"
            except Exception as err:
                ok = False
                detail[name] = f"error: {err}"
        return ok, detail

    def healthz(self) -> tuple[bool, dict]:
        return self._run(self._healthz)

    def readyz(self) -> tuple[bool, dict]:
        return self._run(self._readyz)

    def handle(self, path: str) -> tuple[int, str]:
        """Route a probe request path to (status code, body)."""
        if path.rstrip("/") == "/healthz":
            ok, detail = self.healthz()
        elif path.rstrip("/") == "/readyz":
            ok, detail = self.readyz()
        else:
            return 404, "not found"
        return (200 if ok else 500), json.dumps(detail)


class HealthServer:
    """Serves a HealthChecks registry on the probe address."""

    def __init__(self, checks: HealthChecks, host: str = "127.0.0.1", port: int = 0):
        self.checks = checks
        registry = self.checks

        class Handler(BaseHTTPRequestHandler):
            # Avoid Nagle+delayed-ACK ~40ms stalls per request.
            disable_nagle_algorithm = True
            def do_GET(self):  # noqa: N802 (http.server API)
                code, body = registry.handle(self.path)
                payload = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
