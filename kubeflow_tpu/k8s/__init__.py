from kubeflow_tpu.k8s.errors import (  # noqa: F401
    ApiError,
    NotFoundError,
    ConflictError,
    AlreadyExistsError,
    InvalidError,
    WebhookDeniedError,
    is_not_found,
    is_conflict,
)
from kubeflow_tpu.k8s.objects import (  # noqa: F401
    name_of,
    namespace_of,
    labels_of,
    annotations_of,
    set_controller_reference,
    owner_uid,
    is_controlled_by,
    matches_labels,
    merge_patch,
)
from kubeflow_tpu.k8s.client import Client, retry_on_conflict  # noqa: F401
from kubeflow_tpu.k8s.fake import FakeCluster, AdmissionRequest  # noqa: F401
from kubeflow_tpu.k8s.manager import (  # noqa: F401
    Manager,
    Reconciler,
    Result,
    FakeClock,
    RealClock,
)
from kubeflow_tpu.k8s.real import ClusterConfig, RealClient  # noqa: F401
from kubeflow_tpu.k8s.envtest import EnvtestServer  # noqa: F401
from kubeflow_tpu.k8s.chaos import ChaosClient, FaultConfig  # noqa: F401
from kubeflow_tpu.k8s.fixtures import (  # noqa: F401
    FakeKubelet,
    add_tpu_node_pool,
    add_cpu_node,
)
