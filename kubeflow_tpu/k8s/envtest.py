"""envtest analog: a real HTTP apiserver façade over FakeCluster.

The reference's integration tier boots actual kube-apiserver + etcd
binaries (envtest — reference components/odh-notebook-controller/
controllers/suite_test.go:93-303). Those binaries don't exist in this
environment, so this module serves the FakeCluster's storage over the
Kubernetes REST dialect instead: list/watch with resourceVersion resume,
CRUD with typed Status errors, the status subresource, merge-patch, and
bearer-token auth. RealClient speaks to it exactly as it would to a live
apiserver, which is what makes the managers' production wiring testable
end-to-end without a cluster.

Watch resourceVersions here are cursors into the FakeCluster event log —
opaque strings to clients, which is all the Kubernetes API contract
promises.
"""

from __future__ import annotations

import base64
import json
import re
import ssl as ssl_mod
import threading
import urllib.request
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, unquote, urlparse

from kubeflow_tpu.k8s import rest
from kubeflow_tpu.k8s.errors import ApiError, WebhookDeniedError
from kubeflow_tpu.k8s.fake import FakeCluster

# resource (plural) → kind, derived from the same table the client uses.
_RESOURCE_TO_KIND = {
    (info.group, info.resource): kind for kind, info in rest.KINDS.items()
}

_CORE_RE = re.compile(r"^/api/v1(?:/namespaces/(?P<ns>[^/]+))?/(?P<res>[^/]+)(?:/(?P<name>[^/]+))?(?P<status>/status)?$")
_GROUP_RE = re.compile(r"^/apis/(?P<group>[^/]+)/(?P<version>[^/]+)(?:/namespaces/(?P<ns>[^/]+))?/(?P<res>[^/]+)(?:/(?P<name>[^/]+))?(?P<status>/status)?$")


class _Route:
    def __init__(self, kind: str, namespace: str, name: str, status: bool):
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.status = status


def _parse_path(path: str) -> Optional[_Route]:
    m = _CORE_RE.match(path)
    group = ""
    if not m:
        m = _GROUP_RE.match(path)
        if not m:
            return None
        group = m.group("group")
    kind = _RESOURCE_TO_KIND.get((group, m.group("res")))
    if kind is None:
        return None
    return _Route(
        kind,
        unquote(m.group("ns") or ""),
        unquote(m.group("name") or ""),
        bool(m.group("status")),
    )


def _selector_from_query(qs: dict, key: str = "labelSelector") -> Optional[dict]:
    raw = (qs.get(key) or [""])[0]
    if not raw:
        return None
    out = {}
    for part in raw.split(","):
        k, _, v = part.partition("=")
        if k:
            out[k] = v
    return out


class EnvtestServer:
    """Threaded HTTP apiserver over a FakeCluster.

    ``lock`` guards every cluster access; test code mutating the backing
    cluster directly (FakeKubelet steps, fixtures) must hold it too.
    """

    # Event-log compaction: when the log exceeds 2x this, the oldest half
    # is dropped — watchers resuming from before the horizon get 410 Gone
    # and relist, exactly the etcd-compaction behavior a real apiserver
    # shows. 0 disables (unbounded log).
    MAX_EVENT_LOG = 8192

    def __init__(
        self,
        cluster: Optional[FakeCluster] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        token: str = "",
        crd_dir: Optional[str] = None,
        max_event_log: Optional[int] = None,
    ):
        self.cluster = cluster or FakeCluster()
        self.lock = threading.RLock()
        # Watch streams block on this instead of polling: every write verb
        # notifies under the lock, so a reconcile chain's per-hop latency
        # is wakeup latency, not a poll interval.
        self.event_cond = threading.Condition(self.lock)
        self.token = token
        self.max_event_log = (
            self.MAX_EVENT_LOG if max_event_log is None else max_event_log
        )
        # CRD structural-schema enforcement (422 on violations), from the
        # SAME generated YAMLs the deploy manifests ship. crd_dir="" turns
        # it off explicitly.
        if crd_dir is None:
            import os as _os

            default_dir = _os.path.join(
                _os.path.dirname(__file__), "..", "..", "config", "crd", "bases"
            )
            crd_dir = default_dir if _os.path.isdir(default_dir) else ""
        from kubeflow_tpu.k8s.schema import CRDSchemas

        self.schemas = CRDSchemas.from_dir(crd_dir) if crd_dir else CRDSchemas()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Nagle + delayed-ACK costs ~40ms per hop on
            # loopback; reconcile chains multiply it.
            disable_nagle_algorithm = True

            # -- plumbing --------------------------------------------------
            def log_message(self, *args):
                pass

            def _reply(self, code: int, doc: dict) -> None:
                payload = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _reply_error(self, err: ApiError) -> None:
                message = str(err)
                if isinstance(err, WebhookDeniedError):
                    message = f"admission webhook denied the request: {message}"
                self._reply(
                    err.code,
                    {
                        "kind": "Status",
                        "apiVersion": "v1",
                        "status": "Failure",
                        "reason": err.reason,
                        "code": err.code,
                        "message": message,
                    },
                )

            def _authorized(self) -> bool:
                if not outer.token:
                    return True
                header = self.headers.get("Authorization", "")
                if header == f"Bearer {outer.token}":
                    return True
                self._reply(
                    401,
                    {"kind": "Status", "status": "Failure", "reason": "Unauthorized",
                     "code": 401, "message": "invalid bearer token"},
                )
                return False

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length", 0))
                data = self.rfile.read(length) if length else b"{}"
                return json.loads(data or b"{}")

            # -- verbs -----------------------------------------------------
            def do_GET(self):  # noqa: N802
                if not self._authorized():
                    return
                url = urlparse(self.path)
                route = _parse_path(url.path)
                if route is None:
                    return self._reply(
                        404, {"kind": "Status", "code": 404, "reason": "NotFound",
                              "message": f"no such path {url.path}"})
                qs = parse_qs(url.query)
                try:
                    if route.name:
                        with outer.lock:
                            obj = outer.cluster.get(route.kind, route.name, route.namespace)
                        return self._reply(200, obj)
                    if (qs.get("watch") or ["false"])[0] == "true":
                        return self._stream_watch(route, qs)
                    selector = _selector_from_query(qs)
                    fields = _selector_from_query(qs, "fieldSelector")
                    with outer.lock:
                        items = outer.cluster.list(
                            route.kind, route.namespace, selector, fields
                        )
                        cursor = outer.cluster.event_cursor()
                    info = rest.info_for(route.kind)
                    return self._reply(200, {
                        "kind": f"{route.kind}List",
                        "apiVersion": info.api_version,
                        "metadata": {"resourceVersion": str(cursor)},
                        "items": items,
                    })
                except ApiError as err:
                    return self._reply_error(err)

            def _stream_watch(self, route: _Route, qs: dict) -> None:
                from kubeflow_tpu.k8s.errors import ExpiredError

                try:
                    cursor = int((qs.get("resourceVersion") or ["0"])[0] or 0)
                except ValueError:
                    cursor = 0
                selector = _selector_from_query(qs)
                timeout_s = int((qs.get("timeoutSeconds") or ["0"])[0] or 0)
                # A resourceVersion behind the compaction horizon is 410
                # Gone BEFORE the stream opens (apiserver behavior): the
                # client must relist, not hang on an unresumable watch.
                try:
                    with outer.lock:
                        events, cursor = outer.cluster.drain_events(cursor)
                except ExpiredError as err:
                    return self._reply_error(err)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Connection", "close")
                self.end_headers()
                import time as _time
                deadline = _time.monotonic() + timeout_s if timeout_s else None
                try:
                    while not outer._shutdown.is_set():
                        for ev in events:
                            if ev.kind != route.kind:
                                continue
                            if route.namespace and ev.namespace != route.namespace:
                                continue
                            if selector is not None:
                                from kubeflow_tpu.k8s import objects as obj_util
                                if not obj_util.matches_labels(ev.object, selector):
                                    continue
                            frame = json.dumps(
                                {"type": ev.type, "object": ev.object}
                            ).encode() + b"\n"
                            self.wfile.write(frame)
                            self.wfile.flush()
                        if deadline and _time.monotonic() >= deadline:
                            return
                        try:
                            with outer.event_cond:
                                if (
                                    outer.cluster.event_cursor() <= cursor
                                    and not outer._shutdown.is_set()
                                ):
                                    # Wakes immediately on any write; the
                                    # cap bounds shutdown/deadline checks.
                                    outer.event_cond.wait(0.05)
                                events, cursor = outer.cluster.drain_events(cursor)
                        except ExpiredError:
                            # Compacted PAST an open stream (log overran the
                            # watcher): the in-band 410 ERROR frame, after
                            # which the client relists.
                            frame = json.dumps({
                                "type": "ERROR",
                                "object": {"kind": "Status", "code": 410,
                                           "reason": "Expired",
                                           "message": "too old resource version"},
                            }).encode() + b"\n"
                            self.wfile.write(frame)
                            self.wfile.flush()
                            return
                except (BrokenPipeError, ConnectionResetError):
                    return  # client went away

            def do_POST(self):  # noqa: N802
                if not self._authorized():
                    return
                route = _parse_path(urlparse(self.path).path)
                if route is None or route.name:
                    return self._reply(404, {"kind": "Status", "code": 404,
                                             "reason": "NotFound", "message": "bad path"})
                try:
                    obj = self._body()
                    obj.setdefault("kind", route.kind)
                    obj.setdefault("apiVersion", rest.info_for(route.kind).api_version)
                    if route.namespace:
                        obj.setdefault("metadata", {}).setdefault("namespace", route.namespace)
                    # Remote admission runs WITHOUT the cluster lock held:
                    # webhook handlers call back into this apiserver.
                    obj = outer._run_remote_admission(route.kind, "CREATE", obj, None)
                    outer.schemas.check(obj)  # CRD validation AFTER mutation
                    with outer.lock:
                        created = outer.cluster.create(obj)
                        outer._maybe_compact()
                    return self._reply(201, created)
                except ApiError as err:
                    return self._reply_error(err)

            def do_PUT(self):  # noqa: N802
                if not self._authorized():
                    return
                route = _parse_path(urlparse(self.path).path)
                if route is None or not route.name:
                    return self._reply(404, {"kind": "Status", "code": 404,
                                             "reason": "NotFound", "message": "bad path"})
                try:
                    obj = self._body()
                    obj.setdefault("kind", route.kind)
                    obj.setdefault("apiVersion", rest.info_for(route.kind).api_version)
                    if route.status:
                        with outer.lock:
                            # Schema-check the RESULT of the status write
                            # (stored spec + incoming status) — a real
                            # apiserver validates the status subresource
                            # against the same CRD schema.
                            stored = outer.cluster.get(
                                route.kind, route.name, route.namespace
                            )
                            candidate = dict(stored)
                            candidate["status"] = obj.get("status", {})
                            outer.schemas.check(candidate)
                            out = outer.cluster.update_status(obj)
                            outer._maybe_compact()
                        return self._reply(200, out)
                    with outer.lock:
                        old = outer.cluster.get(route.kind, route.name, route.namespace)
                    obj = outer._run_remote_admission(route.kind, "UPDATE", obj, old)
                    outer.schemas.check(obj)
                    with outer.lock:
                        out = outer.cluster.update(obj)
                        outer._maybe_compact()
                    return self._reply(200, out)
                except ApiError as err:
                    return self._reply_error(err)

            def do_PATCH(self):  # noqa: N802
                if not self._authorized():
                    return
                route = _parse_path(urlparse(self.path).path)
                if route is None or not route.name:
                    return self._reply(404, {"kind": "Status", "code": 404,
                                             "reason": "NotFound", "message": "bad path"})
                try:
                    patch = self._body()
                    if route.kind in outer._remote_webhooks:
                        from kubeflow_tpu.k8s import objects as obj_util

                        with outer.lock:
                            stored = outer.cluster.get(
                                route.kind, route.name, route.namespace
                            )
                        merged = obj_util.merge_patch(stored, patch)
                        merged["metadata"]["resourceVersion"] = stored["metadata"][
                            "resourceVersion"
                        ]
                        merged = outer._run_remote_admission(
                            route.kind, "UPDATE", merged, stored
                        )
                        outer.schemas.check(merged)
                        with outer.lock:
                            out = outer.cluster.update(merged)
                            outer._maybe_compact()
                    else:
                        from kubeflow_tpu.k8s import objects as obj_util

                        # ONE lock window for merge + schema check + apply:
                        # checking a merge computed in an earlier window
                        # could validate a state that never gets stored.
                        with outer.lock:
                            stored = outer.cluster.get(
                                route.kind, route.name, route.namespace
                            )
                            outer.schemas.check(
                                obj_util.merge_patch(stored, patch)
                            )
                            out = outer.cluster.patch(
                                route.kind, route.name, route.namespace, patch
                            )
                            outer._maybe_compact()
                    return self._reply(200, out)
                except ApiError as err:
                    return self._reply_error(err)

            def do_DELETE(self):  # noqa: N802
                if not self._authorized():
                    return
                route = _parse_path(urlparse(self.path).path)
                if route is None or not route.name:
                    return self._reply(404, {"kind": "Status", "code": 404,
                                             "reason": "NotFound", "message": "bad path"})
                try:
                    with outer.lock:
                        outer.cluster.delete(route.kind, route.name, route.namespace)
                        outer._maybe_compact()
                    return self._reply(200, {"kind": "Status", "status": "Success"})
                except ApiError as err:
                    return self._reply_error(err)

        self._shutdown = threading.Event()
        self._remote_webhooks: dict[str, _RemoteWebhook] = {}
        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    def _maybe_compact(self) -> None:
        """Bound the event log (call with ``lock`` held): past 2x the cap,
        drop the oldest half — stragglers see 410 and relist. Also the
        per-write chokepoint, so it wakes blocked watch streams."""
        if self.max_event_log and len(self.cluster.events) > 2 * self.max_event_log:
            self.cluster.compact_events(self.max_event_log)
        self.event_cond.notify_all()

    # -- remote admission (WebhookConfiguration analog) --------------------

    def add_remote_webhook(
        self,
        kind: str = "Notebook",
        mutate_url: str = "",
        validate_url: str = "",
        ca_file: str = "",
    ) -> None:
        """Register AdmissionReview endpoints called on CREATE/UPDATE of
        ``kind`` — what a Mutating/ValidatingWebhookConfiguration does on a
        real apiserver, including serving-cert verification via caBundle
        and failurePolicy: Fail on transport errors."""
        ctx = None
        if ca_file:
            ctx = ssl_mod.create_default_context(cafile=ca_file)
            ctx.check_hostname = False  # cert SAN is the in-cluster svc name
        self._remote_webhooks[kind] = _RemoteWebhook(mutate_url, validate_url, ctx)

    def _post_review(self, hook: _RemoteWebhook, url: str, operation: str,
                     obj: dict, old: Optional[dict]) -> dict:
        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": "envtest",
                "operation": operation,
                "object": obj,
                "oldObject": old,
            },
        }
        http_req = urllib.request.Request(
            url, data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(
                http_req, timeout=10, context=hook.ssl_context
            ) as resp:
                return json.loads(resp.read()).get("response", {})
        except Exception as err:  # failurePolicy: Fail
            raise WebhookDeniedError(f"webhook call failed: {err}") from err

    def _run_remote_admission(
        self, kind: str, operation: str, obj: dict, old: Optional[dict]
    ) -> dict:
        hook = self._remote_webhooks.get(kind)
        if hook is None:
            return obj
        if hook.mutate_url:
            response = self._post_review(hook, hook.mutate_url, operation, obj, old)
            if not response.get("allowed", False):
                raise WebhookDeniedError(
                    response.get("status", {}).get("message", "denied")
                )
            patch_b64 = response.get("patch", "")
            if patch_b64:
                from kubeflow_tpu.webhook.server import apply_json_patch

                ops = json.loads(base64.b64decode(patch_b64))
                obj = apply_json_patch(obj, ops)
        if hook.validate_url:
            response = self._post_review(hook, hook.validate_url, operation, obj, old)
            if not response.get("allowed", False):
                raise WebhookDeniedError(
                    response.get("status", {}).get("message", "denied")
                )
        return obj

    @property
    def host(self) -> str:
        return self._server.server_address[0]

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "EnvtestServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="envtest-apiserver"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._shutdown.set()
        with self.event_cond:
            self.event_cond.notify_all()  # release blocked watch streams
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def client_config(self):
        """A ClusterConfig pointed at this server (plain HTTP)."""
        from kubeflow_tpu.k8s.real import ClusterConfig

        return ClusterConfig(
            host=self.host, port=self.port, scheme="http", token=self.token
        )


@dataclass
class _RemoteWebhook:
    mutate_url: str = ""
    validate_url: str = ""
    ssl_context: Optional[object] = None
