"""Client interface: the seam between controllers and the API server.

Controllers only ever talk through this interface, which makes the fake
cluster (tests), the chaos wrapper (fault injection, reference
components/notebook-controller/chaostests/chaos_test.go:50-59), and a future
real API-server client interchangeable.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Protocol, TypeVar

from kubeflow_tpu.k8s.errors import ConflictError


class Client(Protocol):
    def get(self, kind: str, name: str, namespace: str = "") -> dict: ...

    def list(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[dict] = None,
        field_selector: Optional[dict] = None,
    ) -> list[dict]: ...

    def create(self, obj: dict) -> dict: ...

    def update(self, obj: dict) -> dict: ...

    def update_status(self, obj: dict) -> dict: ...

    def patch(self, kind: str, name: str, namespace: str, patch: dict) -> dict: ...

    def delete(self, kind: str, name: str, namespace: str = "") -> None: ...


T = TypeVar("T")


def retry_on_conflict(
    fn: Callable[[], T],
    attempts: int = 5,
    backoff_s: float = 0.0,
) -> T:
    """client-go retry.RetryOnConflict: re-run read-modify-write on 409.

    Every annotation/finalizer mutation in the reference is wrapped in this
    (e.g. reference culling_controller.go:170-197); same discipline here.
    """
    last: Exception = ConflictError("no attempts made")
    for i in range(attempts):
        try:
            return fn()
        except ConflictError as err:
            last = err
            if backoff_s and i < attempts - 1:
                time.sleep(backoff_s * (2**i))
    raise last
