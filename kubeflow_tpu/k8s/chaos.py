"""Fault-injecting client wrapper: the chaos tier of the test pyramid.

Mirrors the reference's operator-chaos SDK usage
(reference components/notebook-controller/chaostests/chaos_test.go:50-59 and
components/odh-notebook-controller/chaostests/): deterministic per-operation
errors (ErrorRate 1.0), transient faults that deactivate mid-test
(faultCfg.Deactivate), and seeded intermittent failure rates for
convergence-under-flakiness tests.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.errors import ApiError


class InjectedError(ApiError):
    code = 500
    reason = "ChaosInjected"


@dataclass
class FaultConfig:
    """One fault rule: which ops fail, for which kinds, how often."""

    operations: tuple[str, ...]  # subset of get/list/create/update/update_status/patch/delete
    kinds: tuple[str, ...] = ()  # empty = all kinds
    error_rate: float = 1.0
    active: bool = True
    injected_count: int = 0

    def deactivate(self) -> None:
        self.active = False

    def activate(self) -> None:
        self.active = True

    def matches(self, op: str, kind: str, rng: random.Random) -> bool:
        if not self.active or op not in self.operations:
            return False
        if self.kinds and kind not in self.kinds:
            return False
        return rng.random() < self.error_rate


class ChaosClient:
    """Wraps any Client, injecting errors per registered FaultConfig."""

    def __init__(self, inner: Client, seed: int = 0):
        self._inner = inner
        self._faults: list[FaultConfig] = []
        self._rng = random.Random(seed)

    def add_fault(self, fault: FaultConfig) -> FaultConfig:
        self._faults.append(fault)
        return fault

    def _maybe_fail(self, op: str, kind: str) -> None:
        for fault in self._faults:
            if fault.matches(op, kind, self._rng):
                fault.injected_count += 1
                raise InjectedError(f"injected {op} failure for {kind}")

    # -- Client protocol, each op gated ------------------------------------

    def get(self, kind: str, name: str, namespace: str = "") -> dict:
        self._maybe_fail("get", kind)
        return self._inner.get(kind, name, namespace)

    def list(
        self, kind: str, namespace: str = "", label_selector=None,
        field_selector=None,
    ) -> list[dict]:
        self._maybe_fail("list", kind)
        return self._inner.list(kind, namespace, label_selector, field_selector)

    def create(self, obj: dict) -> dict:
        self._maybe_fail("create", obj.get("kind", ""))
        return self._inner.create(obj)

    def update(self, obj: dict) -> dict:
        self._maybe_fail("update", obj.get("kind", ""))
        return self._inner.update(obj)

    def update_status(self, obj: dict) -> dict:
        self._maybe_fail("update_status", obj.get("kind", ""))
        return self._inner.update_status(obj)

    def patch(self, kind: str, name: str, namespace: str, patch: dict) -> dict:
        self._maybe_fail("patch", kind)
        return self._inner.patch(kind, name, namespace, patch)

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        self._maybe_fail("delete", kind)
        return self._inner.delete(kind, name, namespace)
