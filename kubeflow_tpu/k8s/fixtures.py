"""Fake kubelet + TPU node-pool fixtures for the integration tier.

envtest runs a real API server but no kubelet, so StatefulSets never produce
pods; the reference works around this by asserting on STS specs only. Here
we go one step further (SURVEY.md §4 "Implication for the tpu build"): a
FakeKubelet turns StatefulSets into indexed pods, binds them to fake TPU
nodes honoring ``google.com/tpu`` allocatable + topology nodeSelectors, and
marks them Ready — so tests can assert end-to-end "Notebook CR → N Ready
TPU-host pods" and scheduling failures (wrong topology, exhausted pool)
surface as Pending pods, like on a real cluster.
"""

from __future__ import annotations

import copy
from typing import Optional

from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.errors import AlreadyExistsError, NotFoundError
from kubeflow_tpu.k8s.fake import FakeCluster
from kubeflow_tpu.k8s.manager import Manager, Reconciler, Request, Result

POD_INDEX_LABEL = "apps.kubernetes.io/pod-index"
STS_POD_NAME_LABEL = "statefulset.kubernetes.io/pod-name"


def add_tpu_node_pool(
    cluster: FakeCluster,
    accelerator_label: str,
    topology: str,
    hosts: int,
    chips_per_host: int,
    name_prefix: str = "tpu-node",
) -> list[str]:
    """Create ``hosts`` fake Nodes forming one TPU slice's node pool."""
    names = []
    for i in range(hosts):
        name = f"{name_prefix}-{topology}-{i}"
        node = {
            "apiVersion": "v1",
            "kind": "Node",
            "metadata": {
                "name": name,
                "labels": {
                    "cloud.google.com/gke-tpu-accelerator": accelerator_label,
                    "cloud.google.com/gke-tpu-topology": topology,
                },
            },
            "status": {
                "allocatable": {"google.com/tpu": str(chips_per_host)},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }
        try:
            cluster.create(node)
        except AlreadyExistsError:
            pass
        names.append(name)
    return names


def add_cpu_node(cluster: FakeCluster, name: str = "cpu-node-0") -> str:
    try:
        cluster.create(
            {
                "apiVersion": "v1",
                "kind": "Node",
                "metadata": {"name": name, "labels": {}},
                "status": {
                    "allocatable": {},
                    "conditions": [{"type": "Ready", "status": "True"}],
                },
            }
        )
    except AlreadyExistsError:
        pass
    return name


class FakeKubelet(Reconciler):
    """Reconciles StatefulSets into scheduled, Ready, indexed pods."""

    def __init__(self, cluster: FakeCluster, auto_ready: bool = True):
        self.cluster = cluster
        self.auto_ready = auto_ready

    def register(self, manager: Manager) -> None:
        def all_sts(ev):
            return [
                Request(obj_util.name_of(s), obj_util.namespace_of(s))
                for s in self.cluster.list("StatefulSet")
            ]

        def pod_capacity_freed_to_all_sts(ev):
            # Capacity-freed signal: a deleted pod — or one that turned
            # Succeeded (terminal pods release their node's TPU
            # allocatable, see _schedule) — lets OTHER StatefulSets'
            # Unschedulable-Pending pods bind (the real scheduler's
            # retry-on-capacity). Failed pods converge via the owner's
            # own reconcile (it deletes them → a DELETED event lands
            # here). Scoped to these rare transitions so the per-pod
            # create/status chatter of a spawning slice cannot amplify
            # into O(n²) reconciles.
            freed = ev.type == "DELETED" or (
                ev.type == "MODIFIED"
                and ev.object.get("status", {}).get("phase") == "Succeeded"
            )
            return all_sts(ev) if freed else []

        manager.register(
            self,
            for_kind="StatefulSet",
            owns=("Pod",),
            watches=[("Node", all_sts),
                     ("Pod", pod_capacity_freed_to_all_sts)],
            name="FakeKubelet",
        )

    def reconcile(self, req: Request) -> Result:
        try:
            sts = self.cluster.get("StatefulSet", req.name, req.namespace)
        except NotFoundError:
            return Result()
        replicas = sts.get("spec", {}).get("replicas", 1)
        # ONE namespace pod list per reconcile serves the hot-path
        # consumers below (ordinal exists-checks, the scale-down scan,
        # the ready count). The kubelet leg is the spawn path's hot loop
        # (loadtest --wire --profile: sts→pods is ~80% of p50), and
        # relisting per POD made each reconcile O(cluster · replicas)
        # HTTP round-trips. Pods this reconcile creates/updates are
        # folded into the cache by hand, so the view stays coherent
        # without re-listing.
        ns_pods = {
            obj_util.name_of(p): p
            for p in self.cluster.list("Pod", req.namespace)
        }
        # Scheduling state (cluster-wide usage + node list — binding must
        # respect pods in OTHER namespaces too) is LAZY: a steady-state
        # reconcile (all pods exist and bound) pays for neither list.
        # scheduler() always runs before this reconcile creates any pod,
        # so its snapshot is coherent; bindings update `used` in place.
        sched_state: list = []

        def scheduler():
            if not sched_state:
                used: dict[str, int] = {}
                for existing in self.cluster.list("Pod"):
                    node_name = existing.get("spec", {}).get("nodeName")
                    phase = existing.get("status", {}).get("phase")
                    if node_name and phase not in ("Failed", "Succeeded"):
                        used[node_name] = (
                            used.get(node_name, 0) + _pod_tpu_request(existing)
                        )
                sched_state.append((self.cluster.list("Node"), used))
            return sched_state[0]

        for i in range(replicas):
            self._ensure_pod(sts, i, ns_pods, scheduler)
            self._retry_pending(sts, i, ns_pods, scheduler)
        for pod in list(ns_pods.values()):
            if not obj_util.is_controlled_by(sts, pod):
                continue
            idx = pod["metadata"].get("labels", {}).get(POD_INDEX_LABEL)
            # Scale-down: remove pods at ordinals >= replicas (whole-slice stop).
            scale_down = idx is not None and int(idx) >= replicas
            # The real StatefulSet controller deletes Failed pods so they are
            # recreated — preemption recovery converges even without a
            # slice-health controller.
            failed = pod.get("status", {}).get("phase") == "Failed"
            if scale_down or failed:
                try:
                    self.cluster.delete("Pod", obj_util.name_of(pod), req.namespace)
                except NotFoundError:
                    pass
                del ns_pods[obj_util.name_of(pod)]
        self._update_sts_status(sts, ns_pods)
        return Result()

    # -- pod lifecycle -----------------------------------------------------

    def _ensure_pod(self, sts: dict, ordinal: int, ns_pods: dict,
                    scheduler) -> None:
        name = f"{obj_util.name_of(sts)}-{ordinal}"
        namespace = obj_util.namespace_of(sts)
        if name in ns_pods:
            return
        template = copy.deepcopy(sts.get("spec", {}).get("template", {}))
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "namespace": namespace,
                "labels": {
                    **template.get("metadata", {}).get("labels", {}),
                    POD_INDEX_LABEL: str(ordinal),
                    STS_POD_NAME_LABEL: name,
                },
                "annotations": dict(template.get("metadata", {}).get("annotations", {})),
            },
            "spec": copy.deepcopy(template.get("spec", {})),
        }
        pod["spec"]["hostname"] = name
        if sts.get("spec", {}).get("serviceName"):
            pod["spec"]["subdomain"] = sts["spec"]["serviceName"]
        obj_util.set_controller_reference(sts, pod)
        node = self._schedule(pod, scheduler)
        if node:
            pod["spec"]["nodeName"] = node
            pod["status"] = self._running_status(pod) if self.auto_ready else {
                "phase": "Pending",
                "conditions": [{"type": "PodScheduled", "status": "True"}],
            }
        else:
            pod["status"] = {
                "phase": "Pending",
                "conditions": [
                    {
                        "type": "PodScheduled",
                        "status": "False",
                        "reason": "Unschedulable",
                        "message": "0/N nodes match TPU nodeSelector/allocatable",
                    }
                ],
            }
        ns_pods[name] = self.cluster.create(pod) or pod

    def _retry_pending(self, sts: dict, ordinal: int, ns_pods: dict,
                       scheduler) -> None:
        """Reschedule an unschedulable Pending pod once capacity appears."""
        name = f"{obj_util.name_of(sts)}-{ordinal}"
        pod = ns_pods.get(name)
        if pod is None:
            return
        status = pod.get("status", {})
        if status.get("phase") != "Pending" or pod["spec"].get("nodeName"):
            return
        node = self._schedule(pod, scheduler)
        if not node:
            return
        pod["spec"]["nodeName"] = node
        pod = self.cluster.update(pod)
        pod["status"] = self._running_status(pod) if self.auto_ready else {
            "phase": "Pending",
            "conditions": [{"type": "PodScheduled", "status": "True"}],
        }
        ns_pods[name] = self.cluster.update_status(pod) or pod

    def _schedule(self, pod: dict, scheduler) -> Optional[str]:
        """Bind to a node satisfying nodeSelector + google.com/tpu allocatable.

        Terminal pods (Failed/Succeeded) release their resources, as on a
        real cluster — preemption recovery depends on this. ``scheduler``
        lazily supplies (nodes, per-node usage) computed ONCE per
        reconcile; bindings made here update the usage map in place so
        sibling ordinals in the same reconcile see them.
        """
        selector = pod["spec"].get("nodeSelector", {})
        tpu_request = _pod_tpu_request(pod)
        nodes, used = scheduler()
        for node in nodes:
            labels = node.get("metadata", {}).get("labels", {})
            if any(labels.get(k) != v for k, v in selector.items()):
                continue
            allocatable = int(
                node.get("status", {}).get("allocatable", {}).get("google.com/tpu", 0)
            )
            node_name = obj_util.name_of(node)
            if tpu_request and used.get(node_name, 0) + tpu_request > allocatable:
                continue
            used[node_name] = used.get(node_name, 0) + tpu_request
            return node_name
        return None

    def _running_status(self, pod: dict) -> dict:
        return {
            "phase": "Running",
            "conditions": [
                {"type": "PodScheduled", "status": "True"},
                {"type": "Initialized", "status": "True"},
                {"type": "ContainersReady", "status": "True"},
                {"type": "Ready", "status": "True"},
            ],
            "containerStatuses": [
                {
                    "name": c.get("name", ""),
                    "ready": True,
                    "restartCount": 0,
                    "state": {"running": {"startedAt": "2026-01-01T00:00:00Z"}},
                }
                for c in pod["spec"].get("containers", [])
            ],
        }

    def _update_sts_status(self, sts: dict, ns_pods: "dict | None" = None) -> None:
        from kubeflow_tpu.k8s.client import retry_on_conflict

        name, ns = obj_util.name_of(sts), obj_util.namespace_of(sts)
        attempts = [0]

        def write():
            # Whole read-compute-write inside the retry: over the WIRE
            # tier the core controller updates the same StatefulSet
            # concurrently (the replica copy) — a stale rv crashed the
            # kubelet thread mid-loadtest instead of retrying like a real
            # kubelet. The FIRST attempt counts ready pods from this
            # reconcile's own cache (pod Ready status has no writer but
            # this kubelet); a CONFLICT is the signal another writer is
            # active, so every retry re-lists and recomputes fresh.
            fresh = self.cluster.get("StatefulSet", name, ns)
            if attempts[0] == 0 and ns_pods is not None:
                pods = list(ns_pods.values())
            else:
                pods = self.cluster.list("Pod", ns)
            attempts[0] += 1
            ready = 0
            for pod in pods:
                if not obj_util.is_controlled_by(fresh, pod):
                    continue
                for cond in pod.get("status", {}).get("conditions", []):
                    if (cond.get("type") == "Ready"
                            and cond.get("status") == "True"):
                        ready += 1
            fresh["status"] = {
                "replicas": fresh.get("spec", {}).get("replicas", 1),
                "readyReplicas": ready,
            }
            self.cluster.update_status(fresh)

        retry_on_conflict(write)

    # -- fault helpers for preemption tests --------------------------------

    def preempt_pod(self, name: str, namespace: str, reason: str = "TerminationByKubernetes") -> None:
        """Simulate a TPU maintenance/spot preemption: pod dies with a reason."""
        pod = self.cluster.get("Pod", name, namespace)
        pod["status"] = {
            "phase": "Failed",
            "reason": "Preempted",
            "message": f"Pod preempted: {reason}",
            "conditions": [
                {
                    "type": "DisruptionTarget",
                    "status": "True",
                    "reason": reason,
                }
            ],
        }
        self.cluster.update_status(pod)


def _pod_tpu_request(pod: dict) -> int:
    total = 0
    for c in pod.get("spec", {}).get("containers", []):
        total += int(c.get("resources", {}).get("limits", {}).get("google.com/tpu", 0) or 0)
    return total


class FakePodRunner(Reconciler):
    """Runs node-pinned, ownerless, run-to-completion pods — the fake
    analog of a kubelet executing a DaemonSet-style pinned pod (e.g. the
    image pre-puller's): any Pod with ``spec.nodeName`` already set, no
    ownerReferences, and ``restartPolicy: Never`` is driven to
    ``Succeeded`` (image pulls complete instantly in the fake).

    ``fail_images`` lets chaos tests model broken registries: a pod
    whose spec references one of those images lands ``Failed`` instead
    (the pre-puller's retry loop is delete + re-create)."""

    def __init__(self, cluster: FakeCluster, fail_images: frozenset = frozenset()):
        self.cluster = cluster
        self.fail_images = frozenset(fail_images)

    def register(self, manager: Manager) -> None:
        manager.register(self, for_kind="Pod", name="FakePodRunner")

    def reconcile(self, req: Request) -> Result:
        try:
            pod = self.cluster.get("Pod", req.name, req.namespace)
        except NotFoundError:
            return Result()
        spec = pod.get("spec", {})
        meta = pod.get("metadata", {})
        if (
            not spec.get("nodeName")
            or meta.get("ownerReferences")
            or spec.get("restartPolicy") != "Never"
        ):
            return Result()
        if pod.get("status", {}).get("phase") in ("Succeeded", "Failed"):
            return Result()
        images = {
            c.get("image")
            for c in spec.get("containers", []) + spec.get("initContainers", [])
        }
        failed = images & self.fail_images
        pod["status"] = {
            "phase": "Failed" if failed else "Succeeded",
            **(
                {
                    "message": f"image pull failed: {sorted(failed)[0]}",
                    # Failure-time stamp, as a real kubelet records it —
                    # retry backoffs key off THIS, not creationTimestamp.
                    "containerStatuses": [{
                        "name": "done",
                        "state": {"terminated": {
                            "exitCode": 1,
                            "finishedAt": self.cluster._now(),
                        }},
                    }],
                }
                if failed else {}
            ),
        }
        self.cluster.update_status(pod)
        return Result()
