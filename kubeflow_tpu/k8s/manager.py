"""controller-runtime analog: watch wiring, workqueue, reconcile loop.

Mirrors the reference's manager/builder semantics
(reference components/notebook-controller/controllers/notebook_controller.go:778-826
``SetupWithManager`` with For/Owns/Watches + handler.EnqueueRequestsFromMapFunc):
controllers declare which kinds they watch and how watch events map to
reconcile requests; the Manager drains the cluster's event stream into a
deduplicating workqueue and calls ``Reconciler.reconcile`` until the system
is quiescent — i.e. level-triggered reconciliation, the reference's core
failure-recovery story (SURVEY.md §5).
"""

from __future__ import annotations

import heapq
import logging
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from kubeflow_tpu.k8s.fake import WatchEvent


class WatchSource(Protocol):
    """What the Manager needs from a cluster: an ordered event stream.

    FakeCluster (tests) and RealClient (production watch threads) both
    provide it — the reconcile loop is identical against either.
    """

    def drain_events(self, cursor: int) -> tuple[list[WatchEvent], int]: ...

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Request:
    name: str
    namespace: str


@dataclass
class Result:
    requeue_after: float = 0.0  # seconds; 0 = no requeue


class Reconciler:
    """Base reconciler. Subclasses override reconcile()."""

    def reconcile(self, req: Request) -> Result:  # pragma: no cover - interface
        raise NotImplementedError


@dataclass
class _Watch:
    kind: str
    map_fn: Callable[[WatchEvent], list[Request]]


class FakeClock:
    """Deterministic clock for culling/requeue tests."""

    def __init__(self, start: float = 1_700_000_000.0):
        self._t = start

    def __call__(self) -> float:
        return self._t

    def now(self) -> float:
        return self._t

    def advance(self, seconds: float) -> None:
        self._t += seconds


class RealClock:
    """Wall clock for production serving: ``advance`` is a no-op (time
    advances itself), so ``Manager.tick(0)`` fires exactly the requeues
    that have actually come due."""

    def __call__(self) -> float:
        import time

        return time.time()

    def now(self) -> float:
        return self()

    def advance(self, seconds: float) -> None:
        pass


@dataclass
class _Registration:
    reconciler: Reconciler
    watches: list[_Watch]
    name: str


class Manager:
    """Drives registered reconcilers from the cluster's watch-event stream.

    ``run_until_idle`` is the test/e2e entrypoint: it drains events, maps
    them to requests, reconciles, and repeats until no new events or
    requests appear (bounded by ``max_cycles`` to catch livelock bugs).
    Timed requeues (Result.requeue_after) and the culler's periodic wakeups
    are driven by ``tick``.
    """

    def __init__(self, cluster: WatchSource, clock: Optional[FakeClock] = None):
        self.cluster = cluster
        self.clock = clock or FakeClock()
        self._registrations: list[_Registration] = []
        self._cursor = 0
        # (due_time, seq, registration_index, request) heap for requeues.
        # _pending coalesces per (reg, request) to the earliest due time,
        # as controller-runtime's workqueue AddAfter does — stale heap
        # entries are lazily skipped on pop.
        self._timers: list[tuple[float, int, int, Request]] = []
        self._pending: dict[tuple[int, Request], float] = {}
        self._timer_seq = 0
        # Reconcile exceptions seen since the last clear (error-masking
        # guard: tests asserting convergence can check this is empty).
        self.reconcile_errors: list[tuple[str, Request, Exception]] = []
        # Per-(reconciler, request) consecutive-failure counts driving the
        # retry backoff (controller-runtime workqueue: 5ms base doubling
        # to a cap; reset on the first success).
        self._failures: dict[tuple[int, Request], int] = {}

    RETRY_BASE_S = 0.005
    RETRY_CAP_S = 30.0

    @property
    def cursor(self) -> int:
        """Position in the event stream this manager has consumed up to —
        the value callers hand to ``client.wait_for_events`` to block for
        work (the serve loop's one dependency on manager internals)."""
        return self._cursor

    # -- registration ------------------------------------------------------

    def register(
        self,
        reconciler: Reconciler,
        for_kind: str,
        owns: tuple[str, ...] = (),
        watches: Optional[list[tuple[str, Callable[[WatchEvent], list[Request]]]]] = None,
        name: str = "",
    ) -> None:
        watch_list = [_Watch(for_kind, _primary_map_fn)]
        for kind in owns:
            watch_list.append(_Watch(kind, _owner_map_fn(for_kind)))
        for kind, fn in watches or []:
            watch_list.append(_Watch(kind, fn))
        self._registrations.append(
            _Registration(reconciler, watch_list, name or type(reconciler).__name__)
        )

    def watched_kinds(self) -> list[str]:
        """Union of kinds any registered reconciler watches (the set of
        watch streams a production serve loop must open)."""
        kinds: list[str] = []
        for reg in self._registrations:
            for watch in reg.watches:
                if watch.kind not in kinds:
                    kinds.append(watch.kind)
        return kinds

    # -- loop --------------------------------------------------------------

    def run_until_idle(self, max_cycles: int = 200) -> int:
        """Reconcile until quiescent. Returns number of reconcile calls."""
        calls = 0
        for _ in range(max_cycles):
            batch = self._collect_requests()
            if not batch:
                return calls
            for reg_idx, req in batch:
                calls += self._dispatch(reg_idx, req)
        raise RuntimeError(
            f"manager did not quiesce within {max_cycles} cycles "
            "(reconcilers keep mutating watched objects)"
        )

    def tick(self, seconds: float, max_cycles: int = 200) -> int:
        """Advance the clock and fire any requeues that came due."""
        self.clock.advance(seconds)
        calls = 0
        now = self.clock.now()
        while self._timers and self._timers[0][0] <= now:
            due, _, reg_idx, req = heapq.heappop(self._timers)
            # Skip stale entries superseded by a coalesced (earlier) timer.
            if self._pending.get((reg_idx, req)) != due:
                continue
            del self._pending[(reg_idx, req)]
            calls += self._dispatch(reg_idx, req)
        calls += self.run_until_idle(max_cycles)
        return calls

    def next_requeue_in(self) -> Optional[float]:
        live = [d for d in self._pending.values()]
        if not live:
            return None
        return max(0.0, min(live) - self.clock.now())

    def _schedule_requeue(self, reg_idx: int, req: Request, delay: float) -> None:
        key = (reg_idx, req)
        due = self.clock.now() + delay
        existing = self._pending.get(key)
        if existing is not None and existing <= due:
            return  # already scheduled sooner (or same) — coalesce
        self._pending[key] = due
        self._timer_seq += 1
        heapq.heappush(self._timers, (due, self._timer_seq, reg_idx, req))

    def _collect_requests(self) -> list[tuple[int, Request]]:
        events, self._cursor = self.cluster.drain_events(self._cursor)
        seen: set[tuple[int, Request]] = set()
        ordered: list[tuple[int, Request]] = []
        for ev in events:
            for reg_idx, reg in enumerate(self._registrations):
                for watch in reg.watches:
                    if watch.kind != ev.kind:
                        continue
                    for req in watch.map_fn(ev):
                        key = (reg_idx, req)
                        if key not in seen:
                            seen.add(key)
                            ordered.append(key)
        return ordered

    def _dispatch(self, reg_idx: int, req: Request) -> int:
        reg = self._registrations[reg_idx]
        key = (reg_idx, req)
        try:
            result = reg.reconciler.reconcile(req)
        except Exception as err:
            log.exception("%s: reconcile %s/%s failed", reg.name, req.namespace, req.name)
            # controller-runtime rate-limited requeue: exponential backoff
            # per item from a 5ms base — a transient write conflict retries
            # almost immediately instead of stalling the spawn path.
            fails = self._failures.get(key, 0) + 1
            self._failures[key] = fails
            self.reconcile_errors.append((reg.name, req, err))
            # Bound the error log for long-running serve loops; tests read
            # it between run_until_idle calls, long before 1000 entries.
            del self.reconcile_errors[:-1000]
            delay = min(self.RETRY_BASE_S * (2 ** (fails - 1)), self.RETRY_CAP_S)
            self._schedule_requeue(reg_idx, req, delay)
            return 1
        self._failures.pop(key, None)
        if result and result.requeue_after > 0:
            self._schedule_requeue(reg_idx, req, result.requeue_after)
        return 1


def _primary_map_fn(ev: WatchEvent) -> list[Request]:
    return [Request(ev.name, ev.namespace)]


def _owner_map_fn(owner_kind: str) -> Callable[[WatchEvent], list[Request]]:
    """Map an owned object's event to its controlling owner of ``owner_kind``.

    Matches controller-runtime's EnqueueRequestForOwner, which filters on the
    OwnerType — a Pod controlled by a StatefulSet must not enqueue a
    same-named Notebook.
    """

    def map_fn(ev: WatchEvent) -> list[Request]:
        for ref in ev.object.get("metadata", {}).get("ownerReferences", []):
            if ref.get("controller") and ref.get("kind") == owner_kind:
                return [Request(ref.get("name", ""), ev.namespace)]
        return []

    return map_fn
