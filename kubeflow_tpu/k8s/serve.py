"""Production serve loop: the mgr.Start(ctx) analog.

The Manager itself is a synchronous drain-and-reconcile engine (testable
with FakeClock); this module runs it against a live watch stream until
stopped: fire due requeues, reconcile everything the watches surfaced,
then block on the event stream (or the next requeue deadline). Matches
the reference's blocking manager start + signal handler
(components/notebook-controller/main.go:141-147 ``mgr.Start(
ctrl.SetupSignalHandler())``).
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Optional

log = logging.getLogger(__name__)


def install_signal_handlers(stop: threading.Event) -> None:
    """SIGTERM/SIGINT → graceful stop (ctrl.SetupSignalHandler analog)."""

    def _handler(signum, frame):
        log.info("signal %s: shutting down", signal.Signals(signum).name)
        stop.set()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)


def split_addr(addr: str, default_host: str = "0.0.0.0") -> tuple[str, int]:
    """':8080' → ('0.0.0.0', 8080); 'localhost:9' → ('localhost', 9)."""
    host, _, port = addr.rpartition(":")
    return (host or default_host), int(port)


def serve(
    bundle,
    client,
    stop: Optional[threading.Event] = None,
    max_idle_wait: float = 1.0,
    max_iterations: int = 0,
    watchdog=None,
) -> None:
    """Drive ``bundle`` (a ManagerBundle or PlatformBundle) until ``stop``.

    ``client`` is the RealClient whose watch threads feed the manager's
    event stream; they are started here for exactly the kinds the
    registered reconcilers watch. Leadership gating lives in the bundle's
    ``tick``/``run_until_idle`` (non-leaders keep polling for the lease,
    as controller-runtime's leader election does).

    When the bundle exposes a ``health`` HealthChecks registry, a
    ServeWatchdog is registered on readyz (pass ``watchdog`` to override
    the default window): every successful drain cycle beats it, so a loop
    wedged in a hung call — or crash-looping every cycle — turns the
    replica unready instead of serving as a zombie.
    """
    stop = stop or threading.Event()
    manager = bundle.manager
    elector = getattr(bundle, "elector", None)
    watches_started = False

    health = getattr(bundle, "health", None)
    if watchdog is None and health is not None:
        from kubeflow_tpu.k8s.health import ServeWatchdog

        watchdog = ServeWatchdog()
    if watchdog is not None and health is not None:
        watchdog.register(health)

    iterations = 0
    while not stop.is_set():
        # A standby replica never drains the event stream (tick() bails
        # before the manager runs), so its watches would accumulate events
        # unboundedly and waiting on the stream would return immediately
        # forever. Standbys therefore keep their watches unopened and just
        # sleep between lease-acquisition attempts.
        is_standby = elector is not None and not elector.try_acquire()
        if not is_standby and not watches_started:
            if hasattr(client, "start_watches"):
                client.start_watches(manager.watched_kinds())
            watches_started = True

        try:
            if hasattr(bundle, "tick"):
                bundle.tick(0)
            else:
                bundle.run_until_idle()
            if watchdog is not None:
                # Only a COMPLETED cycle beats: a loop that raises every
                # pass (or blocks inside tick) goes unready once the
                # watchdog window lapses.
                watchdog.beat(manager.cursor)
        except Exception:
            # A reconcile bug must not kill the process; level-triggered
            # retry will re-drive it (errors are also recorded on
            # manager.reconcile_errors).
            log.exception("reconcile cycle failed")
            time.sleep(0.5)

        iterations += 1
        if max_iterations and iterations >= max_iterations:
            return

        delay = manager.next_requeue_in()
        timeout = max_idle_wait if delay is None else max(0.0, min(delay, max_idle_wait))
        if not is_standby and hasattr(client, "wait_for_events"):
            client.wait_for_events(manager.cursor, timeout)
        else:
            stop.wait(timeout)
