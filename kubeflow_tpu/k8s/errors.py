"""Typed API errors mirroring Kubernetes apimachinery status reasons.

The reference's error-handling idioms — ``apierrs.IsNotFound``,
``apierrs.IsConflict``, ``retry.RetryOnConflict`` (e.g. reference
components/notebook-controller/controllers/culling_controller.go:170-197) —
are load-bearing for controller correctness, so the same vocabulary exists
here as exception types plus predicate helpers.
"""

from __future__ import annotations


class ApiError(Exception):
    code: int = 500
    reason: str = "InternalError"

    def __init__(self, message: str = ""):
        super().__init__(message or self.reason)
        self.message = message


class NotFoundError(ApiError):
    code = 404
    reason = "NotFound"


class AlreadyExistsError(ApiError):
    code = 409
    reason = "AlreadyExists"


class ConflictError(ApiError):
    """Optimistic-concurrency failure (stale resourceVersion)."""

    code = 409
    reason = "Conflict"


class InvalidError(ApiError):
    code = 422
    reason = "Invalid"


class WebhookDeniedError(ApiError):
    """An admission webhook rejected the request."""

    code = 403
    reason = "Forbidden"


class ExpiredError(ApiError):
    """Watch/list resourceVersion older than the server's retention window
    (HTTP 410 Gone) — the client must relist."""

    code = 410
    reason = "Expired"


def is_not_found(err: Exception) -> bool:
    return isinstance(err, NotFoundError)


def is_conflict(err: Exception) -> bool:
    return isinstance(err, ConflictError)


def is_already_exists(err: Exception) -> bool:
    return isinstance(err, AlreadyExistsError)
