"""Real Kubernetes API-server client: the production Client implementation.

client-go's role, stdlib-only. Satisfies the same ``Client`` protocol the
controllers use against FakeCluster, plus the watch-stream surface the
Manager drains (``drain_events``/``wait_for_events``), so the entire
control plane runs unchanged against a live apiserver (reference
components/notebook-controller/main.go:58-148 — ctrl.GetConfigOrDie +
mgr.Start wire exactly this).

Auth, in order (reference: client-go rest.InClusterConfig / kubeconfig):
- in-cluster: ``KUBERNETES_SERVICE_HOST`` + serviceaccount token/ca files,
- ``$KUBECONFIG`` (or ``~/.kube/config``): current-context cluster/user,
  supporting token, token-file, client-cert, and insecure-skip-verify.

Watches follow the list-then-watch informer contract: one LIST per kind
seeds synthetic ADDED events and a resourceVersion; the WATCH resumes from
it, bookmarks advance it, and 410 Gone falls back to relist. Events land in
an in-process ordered stream identical in shape to FakeCluster's.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import tempfile
import threading
import time
from dataclasses import dataclass, field
from http.client import (
    HTTPConnection,
    HTTPException,
    HTTPResponse,
    HTTPSConnection,
)
from pathlib import Path
from typing import Callable, Iterator, Optional

from kubeflow_tpu.k8s import rest
from kubeflow_tpu.k8s.errors import (
    AlreadyExistsError,
    ApiError,
    ConflictError,
    InvalidError,
    NotFoundError,
    WebhookDeniedError,
)
from kubeflow_tpu.k8s.fake import WatchEvent

log = logging.getLogger(__name__)

SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ConfigError(RuntimeError):
    """No usable cluster configuration found."""


@dataclass
class ClusterConfig:
    """Connection + auth material for one apiserver."""

    host: str
    port: int = 443
    scheme: str = "https"
    token: str = ""
    token_file: str = ""
    ca_file: str = ""
    client_cert_file: str = ""
    client_key_file: str = ""
    insecure_skip_verify: bool = False
    namespace: str = ""  # the SA namespace, when in-cluster

    # -- factories ---------------------------------------------------------

    @classmethod
    def in_cluster(cls, env: Optional[dict] = None, sa_dir: str = SERVICEACCOUNT_DIR) -> "ClusterConfig":
        env = env if env is not None else dict(os.environ)
        host = env.get("KUBERNETES_SERVICE_HOST", "")
        port = int(env.get("KUBERNETES_SERVICE_PORT", "443") or 443)
        token_file = os.path.join(sa_dir, "token")
        ca_file = os.path.join(sa_dir, "ca.crt")
        ns_file = os.path.join(sa_dir, "namespace")
        if not host or not os.path.exists(token_file):
            raise ConfigError("not running in a cluster (no service host/token)")
        namespace = ""
        try:
            namespace = Path(ns_file).read_text().strip()
        except OSError:
            pass
        return cls(
            host=host, port=port, token_file=token_file,
            ca_file=ca_file if os.path.exists(ca_file) else "",
            namespace=namespace,
        )

    @classmethod
    def from_kubeconfig(cls, path: str, context: str = "") -> "ClusterConfig":
        import base64

        import yaml

        try:
            doc = yaml.safe_load(Path(path).read_text())
        except OSError as err:
            raise ConfigError(f"cannot read kubeconfig {path}: {err}") from err
        if not isinstance(doc, dict):
            raise ConfigError(f"kubeconfig {path} is not a mapping")
        ctx_name = context or doc.get("current-context", "")
        ctx = _named(doc.get("contexts", []), ctx_name).get("context", {})
        cluster = _named(doc.get("clusters", []), ctx.get("cluster", "")).get("cluster", {})
        user = _named(doc.get("users", []), ctx.get("user", "")).get("user", {})

        server = cluster.get("server", "")
        if not server:
            raise ConfigError(f"kubeconfig {path}: no server for context {ctx_name!r}")
        scheme, _, rest_part = server.partition("://")
        hostport = rest_part.split("/", 1)[0]
        host, _, port_s = hostport.partition(":")
        port = int(port_s) if port_s else (443 if scheme == "https" else 80)

        def _materialize(data_key: str, file_key: str, src: dict) -> str:
            """Inline *-data beats a file path (kubeconfig precedence)."""
            data = src.get(data_key)
            if data:
                tmp = tempfile.NamedTemporaryFile(
                    mode="wb", delete=False, prefix="kftpu-", suffix=".pem"
                )
                tmp.write(base64.b64decode(data))
                tmp.close()
                return tmp.name
            return src.get(file_key, "")

        return cls(
            host=host,
            port=port,
            scheme=scheme or "https",
            token=user.get("token", ""),
            token_file=user.get("tokenFile", ""),
            ca_file=_materialize("certificate-authority-data", "certificate-authority", cluster),
            client_cert_file=_materialize("client-certificate-data", "client-certificate", user),
            client_key_file=_materialize("client-key-data", "client-key", user),
            insecure_skip_verify=bool(cluster.get("insecure-skip-verify", False)),
            namespace=ctx.get("namespace", ""),
        )

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "ClusterConfig":
        """in-cluster first, then $KUBECONFIG, then ~/.kube/config."""
        env = env if env is not None else dict(os.environ)
        try:
            return cls.in_cluster(env)
        except ConfigError:
            pass
        kubeconfig = env.get("KUBECONFIG", "")
        if kubeconfig:
            return cls.from_kubeconfig(kubeconfig.split(os.pathsep)[0])
        home = env.get("HOME") or os.path.expanduser("~")
        default = os.path.join(home, ".kube", "config")
        if os.path.exists(default):
            return cls.from_kubeconfig(default)
        raise ConfigError(
            "no cluster configuration: not in-cluster, no $KUBECONFIG, "
            "no ~/.kube/config"
        )

    # -- connection --------------------------------------------------------

    def bearer_token(self) -> str:
        if self.token:
            return self.token
        if self.token_file:
            try:
                # Re-read every call: SA tokens rotate (BoundServiceAccountTokenVolume).
                return Path(self.token_file).read_text().strip()
            except OSError:
                return ""
        return ""

    def make_connection(self, timeout: Optional[float] = 30.0):
        # (Client-side TCP_NODELAY is already set by http.client's
        # connect(); the server handlers disable Nagle too — both sides
        # matter for the ~40ms delayed-ACK stall per request.)
        if self.scheme == "http":
            return HTTPConnection(self.host, self.port, timeout=timeout)
        ctx = ssl.create_default_context()
        if self.ca_file:
            ctx.load_verify_locations(self.ca_file)
        if self.client_cert_file:
            ctx.load_cert_chain(self.client_cert_file, self.client_key_file or None)
        if self.insecure_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        return HTTPSConnection(self.host, self.port, context=ctx, timeout=timeout)


def _named(items: list, name: str) -> dict:
    for item in items or []:
        if item.get("name") == name:
            return item
    return {}


def _error_for(status: int, body: bytes) -> ApiError:
    message = ""
    reason = ""
    try:
        doc = json.loads(body or b"{}")
        message = doc.get("message", "")
        reason = doc.get("reason", "")
    except (json.JSONDecodeError, AttributeError):
        message = body.decode(errors="replace")[:300]
    if status == 404:
        return NotFoundError(message or "not found")
    if status == 409:
        if reason == "AlreadyExists":
            return AlreadyExistsError(message or "already exists")
        return ConflictError(message or "conflict")
    if status in (400, 422):
        return InvalidError(message or "invalid")
    if status == 403 and "admission webhook" in message:
        return WebhookDeniedError(message)
    err = ApiError(message or f"HTTP {status}")
    err.code = status
    return err


class RealClient:
    """HTTP Client + watch source against a live kube-apiserver."""

    def __init__(self, config: ClusterConfig, user_agent: str = "kubeflow-tpu-controller"):
        self.config = config
        self.user_agent = user_agent
        # Per-THREAD keep-alive connections. A shared connection would need
        # a lock, and a lock deadlocks re-entrant paths: a reconciler's
        # in-flight update triggers admission, whose webhook handler reads
        # through this same client from the webhook server's thread.
        self._local = threading.local()
        # Watch event stream (FakeCluster-compatible surface for Manager).
        # Cursors are ABSOLUTE counters; the drained prefix is discarded
        # (``_events_base`` tracks how much) so a long-running process
        # doesn't hold every event ever seen. One consumer per client.
        self.events: list[WatchEvent] = []
        self._events_base = 0
        self._events_lock = threading.Lock()
        self._events_cond = threading.Condition(self._events_lock)
        self._watchers: list[_Watcher] = []
        self._stopped = threading.Event()

    # -- HTTP plumbing -----------------------------------------------------

    def _headers(self, content_type: str = "") -> dict:
        headers = {
            "Accept": "application/json",
            "User-Agent": self.user_agent,
        }
        token = self.config.bearer_token()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        if content_type:
            headers["Content-Type"] = content_type
        return headers

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        content_type: str = "application/json",
    ) -> dict:
        payload = json.dumps(body).encode() if body is not None else None
        last_err: Optional[Exception] = None
        for attempt in range(2):  # one reconnect on a dead keep-alive socket
            conn = getattr(self._local, "conn", None)
            try:
                if conn is None:
                    conn = self._local.conn = self.config.make_connection()
                conn.request(
                    method, path, body=payload,
                    headers=self._headers(content_type if payload else ""),
                )
                resp = conn.getresponse()
                data = resp.read()
                status = resp.status
            except (OSError, ssl.SSLError, HTTPException) as err:
                # HTTPException covers IncompleteRead/BadStatusLine/
                # CannotSendRequest from a dead keep-alive socket — the
                # poisoned connection must be dropped, not cached.
                try:
                    conn.close()
                except Exception:
                    pass
                self._local.conn = None
                last_err = err
                continue
            if status >= 400:
                raise _error_for(status, data)
            return json.loads(data) if data else {}
        raise ApiError(f"apiserver unreachable: {last_err}")

    # -- Client protocol ---------------------------------------------------

    def get(self, kind: str, name: str, namespace: str = "") -> dict:
        obj = self._request("GET", rest.object_path(kind, name, namespace))
        return _ensure_tkg(obj, kind)

    def list(
        self,
        kind: str,
        namespace: str = "",
        label_selector: Optional[dict] = None,
        field_selector: Optional[dict] = None,
    ) -> list[dict]:
        path = rest.collection_path(kind, namespace) + rest.list_query(
            label_selector, field_selector=field_selector
        )
        doc = self._request("GET", path)
        return [_ensure_tkg(item, kind) for item in doc.get("items", [])]

    def create(self, obj: dict) -> dict:
        kind = obj.get("kind", "")
        obj = _ensure_tkg(dict(obj), kind)
        ns = obj.get("metadata", {}).get("namespace", "")
        out = self._request("POST", rest.collection_path(kind, ns), body=obj)
        return _ensure_tkg(out, kind)

    def update(self, obj: dict) -> dict:
        kind = obj.get("kind", "")
        obj = _ensure_tkg(dict(obj), kind)
        meta = obj.get("metadata", {})
        path = rest.object_path(kind, meta.get("name", ""), meta.get("namespace", ""))
        return _ensure_tkg(self._request("PUT", path, body=obj), kind)

    def update_status(self, obj: dict) -> dict:
        kind = obj.get("kind", "")
        obj = _ensure_tkg(dict(obj), kind)
        meta = obj.get("metadata", {})
        path = rest.status_path(kind, meta.get("name", ""), meta.get("namespace", ""))
        return _ensure_tkg(self._request("PUT", path, body=obj), kind)

    def patch(self, kind: str, name: str, namespace: str, patch: dict) -> dict:
        out = self._request(
            "PATCH",
            rest.object_path(kind, name, namespace),
            body=patch,
            content_type="application/merge-patch+json",
        )
        return _ensure_tkg(out, kind)

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        self._request("DELETE", rest.object_path(kind, name, namespace))

    def exists(self, kind: str, name: str, namespace: str = "") -> bool:
        try:
            self.get(kind, name, namespace)
            return True
        except NotFoundError:
            return False

    # -- watch machinery ---------------------------------------------------

    def start_watches(self, kinds: list[str], namespace: str = "") -> None:
        """One list-then-watch loop per kind, feeding the shared stream."""
        for kind in kinds:
            if any(w.kind == kind for w in self._watchers):
                continue
            watcher = _Watcher(self, kind, namespace)
            self._watchers.append(watcher)
            watcher.start()

    def wait_for_events(self, cursor: int, timeout: float) -> bool:
        """Block until events beyond ``cursor`` exist (or timeout)."""
        with self._events_cond:
            if self._events_base + len(self.events) > cursor:
                return True
            self._events_cond.wait(timeout)
            return self._events_base + len(self.events) > cursor

    def drain_events(self, cursor: int) -> tuple[list[WatchEvent], int]:
        with self._events_lock:
            start = max(0, cursor - self._events_base)
            new = list(self.events[start:])
            # Drop everything up to and including what this drain returned;
            # the absolute counter keeps older cursors harmless (they just
            # miss already-consumed history, which a single consumer never
            # asks for).
            consumed = start + len(new)
            del self.events[:consumed]
            self._events_base += consumed
            return new, self._events_base

    def _push_event(self, ev: WatchEvent) -> None:
        with self._events_cond:
            self.events.append(ev)
            self._events_cond.notify_all()

    def stop(self) -> None:
        self._stopped.set()
        for w in self._watchers:
            w.stop()
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            self._local.conn = None


def _ensure_tkg(obj: dict, kind: str) -> dict:
    """List items come back without apiVersion/kind; controllers rely on both."""
    if kind and not obj.get("kind"):
        obj["kind"] = kind
        obj.setdefault("apiVersion", rest.info_for(kind).api_version)
    return obj


class _RelistRequired(ApiError):
    """Watch resourceVersion expired (410 Gone): list-then-watch again."""


class _Watcher(threading.Thread):
    """List-then-watch loop for one kind (an informer's reflector).

    client-go reflector semantics (consumed by the reference at
    components/notebook-controller/main.go:58-148):
    - every watch request carries ``timeoutSeconds`` so the server closes
      the stream on a bounded cadence (clean EOF → resume from last rv),
    - the watch socket carries a READ DEADLINE slightly past that server
      timeout plus TCP keepalive, so a silently-dead peer (NAT drop,
      node freeze) surfaces as a timeout instead of wedging the watcher
      forever,
    - transient connection errors RESUME the watch from the last-seen
      resourceVersion — no relist, no duplicate-ADDED reseed storm; only
      410 Gone (or repeated resume failures) falls back to a full relist.
    """

    RELIST_BACKOFF = (0.2, 0.5, 1.0, 2.0, 5.0)
    # Server-side stream cadence; client-go uses 5-10 min. The socket read
    # deadline adds slack for the final frame to arrive.
    WATCH_TIMEOUT_SECONDS = 240
    SOCKET_DEADLINE_SLACK = 30.0
    # After this many consecutive failed resume attempts, assume the rv is
    # poisoned (e.g. apiserver restored from backup) and relist.
    MAX_RESUME_FAILURES = 4

    def __init__(self, client: RealClient, kind: str, namespace: str):
        super().__init__(daemon=True, name=f"watch-{kind.lower()}")
        self.client = client
        self.kind = kind
        self.namespace = namespace
        self._stop = threading.Event()
        self._conn = None
        # Last rv DELIVERED to the stream — updated per event so a
        # mid-stream exception does not lose progress (resuming from the
        # pre-call rv would replay the whole delta window as duplicates).
        self._resume_rv = ""
        # Did the most recent watch attempt get a 2xx stream open? A
        # success resets the failure counters so they count CONSECUTIVE
        # failures, not lifetime disconnects (a healthy watcher must not
        # drift toward forced relists over days of routine reconnects).
        self._connected_ok = False

    def stop(self) -> None:
        self._stop.set()
        if self._conn is not None:
            try:
                self._conn.close()  # unblocks the blocking read
            except Exception:
                pass

    def run(self) -> None:
        backoff_idx = 0
        rv = ""
        resume_failures = 0
        while not self._stop.is_set():
            try:
                if not rv:
                    rv = self._list_and_seed()
                    backoff_idx = 0
                rv = self._watch_from(rv)
                backoff_idx = 0
                resume_failures = 0
            except _RelistRequired:
                self._resume_rv = ""
                if self._stop.is_set():
                    return
                log.info("watch %s: resourceVersion expired; relisting", self.kind)
                rv = ""
                resume_failures = 0
            except Exception as err:
                if self._stop.is_set():
                    return
                # Events already delivered before the failure advance the
                # resume point — never replay them.
                rv = self._resume_rv or rv
                if self._connected_ok:
                    # The failed cycle DID stream successfully first: this
                    # is a fresh disconnect, not the next in a failure run.
                    backoff_idx = 0
                    resume_failures = 0
                    self._connected_ok = False
                delay = self.RELIST_BACKOFF[min(backoff_idx, len(self.RELIST_BACKOFF) - 1)]
                backoff_idx += 1
                if rv:
                    resume_failures += 1
                    if resume_failures >= self.MAX_RESUME_FAILURES:
                        log.warning(
                            "watch %s: %s; %d failed resumes — relisting in %.1fs",
                            self.kind, err, resume_failures, delay,
                        )
                        rv = ""
                        resume_failures = 0
                    else:
                        log.warning(
                            "watch %s: %s; resuming from rv=%s in %.1fs",
                            self.kind, err, rv, delay,
                        )
                else:
                    log.warning("watch %s: %s; relisting in %.1fs", self.kind, err, delay)
                self._stop.wait(delay)

    def _list_and_seed(self) -> str:
        path = rest.collection_path(self.kind, self.namespace)
        doc = self.client._request("GET", path)
        for item in doc.get("items", []):
            item = _ensure_tkg(item, self.kind)
            meta = item.get("metadata", {})
            self.client._push_event(
                WatchEvent("ADDED", self.kind, meta.get("namespace", ""), meta.get("name", ""), item)
            )
        return doc.get("metadata", {}).get("resourceVersion", "")

    def _open_watch_connection(self):
        """Watch connection with a read deadline + TCP keepalive (a watch
        with no deadline on a silently-dead peer blocks forever)."""
        conn = self.client.config.make_connection(
            timeout=self.WATCH_TIMEOUT_SECONDS + self.SOCKET_DEADLINE_SLACK
        )
        conn.connect()
        sock = conn.sock
        try:
            import socket as socketmod

            sock.setsockopt(socketmod.SOL_SOCKET, socketmod.SO_KEEPALIVE, 1)
            # Linux knobs; absent on other platforms — keepalive still on.
            for opt, val in (
                ("TCP_KEEPIDLE", 30), ("TCP_KEEPINTVL", 10), ("TCP_KEEPCNT", 3),
            ):
                if hasattr(socketmod, opt):
                    sock.setsockopt(
                        socketmod.IPPROTO_TCP, getattr(socketmod, opt), val
                    )
        except OSError:  # pragma: no cover — keepalive is best-effort
            pass
        return conn

    def _watch_from(self, rv: str) -> str:
        """Stream watch events; returns the latest rv on clean EOF or a
        retriable disconnect (caller resumes), raises _RelistRequired on
        410 Gone."""
        self._resume_rv = rv
        while not self._stop.is_set():
            path = rest.collection_path(self.kind, self.namespace) + rest.list_query(
                watch=True, resource_version=rv, allow_bookmarks=True,
                timeout_seconds=self.WATCH_TIMEOUT_SECONDS,
            )
            self._conn = self._open_watch_connection()
            try:
                self._conn.request("GET", path, headers=self.client._headers())
                resp = self._conn.getresponse()
                if resp.status == 410:
                    resp.read()
                    raise _RelistRequired("410 Gone: relist required")
                if resp.status >= 400:
                    raise _error_for(resp.status, resp.read())
                self._connected_ok = True
                for line in _iter_lines(resp):
                    if self._stop.is_set():
                        return rv
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    etype = ev.get("type", "")
                    obj = ev.get("object", {}) or {}
                    if etype == "BOOKMARK":
                        rv = obj.get("metadata", {}).get("resourceVersion", rv)
                        self._resume_rv = rv
                        continue
                    if etype == "ERROR":
                        code = obj.get("code", 0)
                        if code == 410:
                            raise _RelistRequired("410 Gone: relist required")
                        raise ApiError(f"watch error event: {obj.get('message', obj)}")
                    obj = _ensure_tkg(obj, self.kind)
                    meta = obj.get("metadata", {})
                    rv = meta.get("resourceVersion", rv)
                    self._resume_rv = rv
                    self.client._push_event(
                        WatchEvent(
                            etype, self.kind,
                            meta.get("namespace", ""), meta.get("name", ""), obj,
                        )
                    )
                # Clean EOF (server-side timeout): loop re-watches from rv.
            finally:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None
        return rv


def _iter_lines(resp: HTTPResponse) -> Iterator[bytes]:
    """Newline-delimited JSON frames from a (possibly chunked) stream."""
    buf = b""
    while True:
        chunk = resp.read1(65536) if hasattr(resp, "read1") else resp.read(65536)
        if not chunk:
            if buf.strip():
                yield buf
            return
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if line.strip():
                yield line
