"""Lease-based leader election.

The reference enables single-active-manager semantics via controller-runtime's
leader election (reference components/notebook-controller/main.go:87-94
``LeaderElection: enableLeaderElection, LeaderElectionID:
"kubeflow-notebook-controller"``; ODH main.go:241-242). controller-runtime
implements that on a coordination.k8s.io/v1 ``Lease``; this module implements
the same protocol against the Client interface so two Manager processes
never reconcile concurrently.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from kubeflow_tpu.k8s.client import Client
from kubeflow_tpu.k8s.errors import AlreadyExistsError, ConflictError, NotFoundError

UPSTREAM_LEASE = "kubeflow-notebook-controller"
PLATFORM_LEASE = "odh-notebook-controller"


class LeaderElector:
    """Acquire/renew/release one named Lease.

    Protocol (matches client-go leaderelection resourcelock semantics):
    - acquire: create the Lease, or take it over once ``renewTime +
      leaseDurationSeconds`` has passed; stale-resourceVersion conflicts
      mean another candidate won the race.
    - renew: update ``renewTime`` while holding.
    - release: zero out ``holderIdentity`` so the next candidate acquires
      immediately instead of waiting out the lease.
    """

    def __init__(
        self,
        client: Client,
        lease_name: str,
        namespace: str,
        identity: str,
        lease_duration: float = 15.0,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.client = client
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity
        self.lease_duration = lease_duration
        self.clock = clock or time.time
        self.transitions = 0

    # -- helpers -----------------------------------------------------------

    def _new_lease(self) -> dict:
        now = self.clock()
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.lease_name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_duration),
                "acquireTime": now,
                "renewTime": now,
                "leaseTransitions": self.transitions,
            },
        }

    def _expired(self, lease: dict) -> bool:
        spec = lease.get("spec", {})
        renew = spec.get("renewTime", 0.0)
        duration = spec.get("leaseDurationSeconds", self.lease_duration)
        return self.clock() >= renew + duration

    # -- protocol ----------------------------------------------------------

    def try_acquire(self) -> bool:
        """One acquire-or-renew attempt. Returns True iff we hold the lease."""
        try:
            lease = self.client.get("Lease", self.lease_name, self.namespace)
        except NotFoundError:
            try:
                self.client.create(self._new_lease())
                return True
            except (AlreadyExistsError, ConflictError):
                return False

        spec = lease.setdefault("spec", {})
        holder = spec.get("holderIdentity", "")
        if holder == self.identity:
            spec["renewTime"] = self.clock()
            try:
                self.client.update(lease)
                return True
            except (ConflictError, NotFoundError):
                return False
        if holder and not self._expired(lease):
            return False
        # Vacant or expired: take over.
        self.transitions = spec.get("leaseTransitions", 0) + 1
        spec.update(
            holderIdentity=self.identity,
            acquireTime=self.clock(),
            renewTime=self.clock(),
            leaseTransitions=self.transitions,
        )
        try:
            self.client.update(lease)
            return True
        except (ConflictError, NotFoundError):
            return False

    def is_leader(self) -> bool:
        try:
            lease = self.client.get("Lease", self.lease_name, self.namespace)
        except NotFoundError:
            return False
        spec = lease.get("spec", {})
        return spec.get("holderIdentity") == self.identity and not self._expired(lease)

    def release(self) -> None:
        """Graceful handoff on shutdown (client-go ReleaseOnCancel)."""
        try:
            lease = self.client.get("Lease", self.lease_name, self.namespace)
        except NotFoundError:
            return
        if lease.get("spec", {}).get("holderIdentity") != self.identity:
            return
        lease["spec"]["holderIdentity"] = ""
        lease["spec"]["renewTime"] = 0.0
        try:
            self.client.update(lease)
        except (ConflictError, NotFoundError):
            pass
