"""Declarative SLO objectives + multi-window burn-rate alerting.

An :class:`Objective` names a signal in a :class:`SignalHub` and a
target — "TTFT p95 ≤ 500ms with a 5% error budget". The
:class:`SLOEngine` evaluates every objective over three horizons (fast
1m/5m + slow 30m, the Google SRE multi-window recipe) and converts each
into a **burn rate**: the ratio of the observed bad fraction to the
budgeted bad fraction. Burn 1.0 = exactly on budget; burn 14.4 over the
fast windows = the budget gone in ~2 days at that pace — page now.

Alert logic:

- **fast alert**: burn ≥ ``fast_burn`` in BOTH fast windows (the 5m
  window confirms the 1m spike is not a blip);
- **slow alert**: burn ≥ ``slow_burn`` in the slow window;
- **breaching** latches on either and clears only when every burn has
  fallen below ``clear_factor`` × its threshold — hysteresis, so a
  burn oscillating around the line doesn't flap the alert;
- windows with fewer than ``min_events`` observations contribute burn
  0 (no traffic is not an outage).

On a fresh breach the engine bumps ``tpu_slo_breach_total``, and — when
tracing is enabled — emits a one-shot ``slo.breach`` span carrying the
burn numbers, so the alert lands in the same ring buffer an operator is
already tailing at ``/debug/traces``. Every evaluation refreshes the
``tpu_slo_burn_rate{objective,window}`` gauge.

Three objective kinds cover the repo's SLOs:

- ``latency``: fraction of histogram samples over ``threshold``
  (TTFT p95, inter-token p95);
- ``ratio``: bad counter / total counter (error+shed ratio);
- ``gauge``: fraction of recent windows where any child of a gauge
  exceeded ``threshold`` (per-replica queue-wait p95 — already a
  quantile replica-side, so window-minutes is the honest aggregate).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

from kubeflow_tpu.observability import tracing
from kubeflow_tpu.observability.signals import SignalHub

_KINDS = ("latency", "ratio", "gauge")


@dataclass(frozen=True)
class Objective:
    """One SLO: a signal, a target, and an error budget."""

    name: str
    kind: str                 # "latency" | "ratio" | "gauge"
    signal: str               # hub signal the bad-fraction comes from
    threshold: float = 0.0    # latency/gauge: the "bad" line (seconds)
    total_signal: str = ""    # ratio: denominator counter
    budget: float = 0.05      # allowed bad fraction (error budget)
    description: str = ""

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"objective {self.name!r}: kind must be one of {_KINDS}, "
                f"got {self.kind!r}"
            )
        if not (0.0 < self.budget <= 1.0):
            raise ValueError(
                f"objective {self.name!r}: budget must be in (0, 1], "
                f"got {self.budget}"
            )
        if self.kind == "ratio" and not self.total_signal:
            raise ValueError(
                f"objective {self.name!r}: ratio kind needs total_signal"
            )
        if self.kind in ("latency", "gauge") and self.threshold <= 0:
            raise ValueError(
                f"objective {self.name!r}: {self.kind} kind needs a "
                f"threshold > 0"
            )


def default_objectives(*, ttft_p95_s: float = 0.5,
                       inter_token_p95_s: float = 0.2,
                       queue_wait_p95_s: float = 0.25,
                       budget: float = 0.05) -> Tuple[Objective, ...]:
    """The serving fleet's stock SLOs, thresholds overridable via
    KUBEFLOW_TPU_SLO_* (see slo_from_env). A latency objective with
    budget 0.05 reads as 'p95 ≤ threshold'."""
    return (
        Objective(
            "ttft_p95", "latency", "ttft_s", threshold=ttft_p95_s,
            budget=budget,
            description="gateway-measured time to first token",
        ),
        Objective(
            "inter_token_p95", "latency", "inter_token_s",
            threshold=inter_token_p95_s, budget=budget,
            description="gateway-measured gap between streamed tokens",
        ),
        Objective(
            "error_ratio", "ratio", "bad_requests",
            total_signal="requests", budget=budget,
            description="errors + sheds over all gateway requests",
        ),
        Objective(
            "queue_wait_p95", "gauge", "replica_queue_wait_p95_s",
            threshold=queue_wait_p95_s, budget=budget,
            description="windows where any replica's queue-wait p95 "
                        "exceeded the target",
        ),
    )


@dataclass
class _State:
    breaching: bool = False
    breaches_total: int = 0
    last_burns: dict = field(default_factory=dict)


class SLOEngine:
    """Evaluates objectives against a hub; owns breach latches."""

    def __init__(self, hub: SignalHub, objectives, *,
                 fast_windows: Tuple[float, float] = (60.0, 300.0),
                 slow_window: float = 1800.0,
                 fast_burn: float = 14.4, slow_burn: float = 2.0,
                 clear_factor: float = 0.5, min_events: int = 10,
                 clock: Optional[Callable[[], float]] = None,
                 metrics=None):
        objectives = tuple(objectives)
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        if not (fast_windows[0] < fast_windows[1] < slow_window):
            raise ValueError(
                "windows must be ordered fast[0] < fast[1] < slow, got "
                f"{fast_windows} / {slow_window}"
            )
        if not (0.0 < clear_factor < 1.0):
            raise ValueError(
                f"clear_factor must be in (0, 1), got {clear_factor}"
            )
        self.hub = hub
        self.objectives = objectives
        self.fast_windows = (float(fast_windows[0]), float(fast_windows[1]))
        self.slow_window = float(slow_window)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.clear_factor = float(clear_factor)
        self.min_events = int(min_events)
        self.clock = clock or time.monotonic
        self.metrics = metrics
        self._lock = threading.Lock()
        self._state = {o.name: _State() for o in objectives}

    def _burn(self, obj: Objective, over_s: float, now: float) -> float:
        """Burn rate of one objective over one horizon; 0.0 when the
        horizon holds too little evidence to judge."""
        hub = self.hub
        if obj.kind == "latency":
            if hub.event_count(obj.signal, over_s, now=now) < self.min_events:
                return 0.0
            frac, _held = hub.fraction_over(
                obj.signal, obj.threshold, over_s, now=now
            )
            return frac / obj.budget
        if obj.kind == "ratio":
            total = hub.counter_sum(obj.total_signal, over_s, now=now)
            if total < self.min_events:
                return 0.0
            bad = hub.counter_sum(obj.signal, over_s, now=now)
            return (bad / total) / obj.budget
        # gauge: bad window-fraction; need >= 2 observed windows so one
        # scrape can't claim 100% badness.
        bad, total = hub.gauge_windows_over(
            obj.signal, obj.threshold, over_s, now=now
        )
        if total < 2:
            return 0.0
        return (bad / total) / obj.budget

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation pass: burns per window, alert flags, latch
        transitions, metric + span emission. Cheap (pure dict math over
        the hub's rings) — the gateway runs it every probe interval."""
        now = self.clock() if now is None else now
        fast_a, fast_b = self.fast_windows
        report: dict = {"now": round(now, 3), "objectives": {},
                        "breaching": []}
        with self._lock:
            for obj in self.objectives:
                burns = {
                    f"{int(w)}s": self._burn(obj, w, now)
                    for w in (fast_a, fast_b, self.slow_window)
                }
                fast_alert = (burns[f"{int(fast_a)}s"] >= self.fast_burn
                              and burns[f"{int(fast_b)}s"] >= self.fast_burn)
                slow_alert = burns[f"{int(self.slow_window)}s"] >= self.slow_burn
                st = self._state[obj.name]
                newly = (fast_alert or slow_alert) and not st.breaching
                if newly:
                    st.breaching = True
                    st.breaches_total += 1
                elif st.breaching:
                    fast_clear = self.clear_factor * self.fast_burn
                    slow_clear = self.clear_factor * self.slow_burn
                    if (max(burns[f"{int(fast_a)}s"],
                            burns[f"{int(fast_b)}s"]) < fast_clear
                            and burns[f"{int(self.slow_window)}s"]
                            < slow_clear):
                        st.breaching = False
                st.last_burns = burns
                if self.metrics is not None:
                    for window, burn in burns.items():
                        self.metrics.slo_burn_rate.labels(
                            objective=obj.name, window=window
                        ).set(burn)
                    if newly:
                        self.metrics.slo_breach_total.labels(
                            objective=obj.name
                        ).inc()
                if newly and tracing.enabled():
                    sp = tracing.get_tracer("slo").begin_span(
                        "slo.breach",
                        **{
                            "slo.objective": obj.name,
                            "slo.kind": obj.kind,
                            "slo.budget": obj.budget,
                        },
                    )
                    sp.add_event("slo.burn", dict(burns))
                    sp.end()
                report["objectives"][obj.name] = {
                    "kind": obj.kind,
                    "threshold": obj.threshold,
                    "budget": obj.budget,
                    "burn": {k: round(v, 4) for k, v in burns.items()},
                    "fast_alert": fast_alert,
                    "slow_alert": slow_alert,
                    "breaching": st.breaching,
                    "breaches_total": st.breaches_total,
                }
                if st.breaching:
                    report["breaching"].append(obj.name)
        return report


def slo_from_env() -> tuple:
    """(objectives, engine_kwargs) from KUBEFLOW_TPU_SLO_*. Latency
    thresholds are milliseconds in the env (operator-friendly), seconds
    internally. Raises on garbage rather than guessing."""
    import os

    from kubeflow_tpu.webhook.tpu_env import (
        KUBEFLOW_TPU_SLO_ERROR_BUDGET,
        KUBEFLOW_TPU_SLO_FAST_BURN,
        KUBEFLOW_TPU_SLO_INTER_TOKEN_P95_MS,
        KUBEFLOW_TPU_SLO_QUEUE_WAIT_P95_MS,
        KUBEFLOW_TPU_SLO_SLOW_BURN,
        KUBEFLOW_TPU_SLO_TTFT_P95_MS,
    )

    def _positive(name, default):
        value = os.environ.get(name, "").strip()
        if not value:
            return default
        try:
            got = float(value)
        except ValueError:
            got = 0.0
        if got <= 0:
            raise ValueError(f"{name}={value!r}: want a number > 0")
        return got

    budget = _positive(KUBEFLOW_TPU_SLO_ERROR_BUDGET, 0.05)
    if budget > 1.0:
        raise ValueError(
            f"{KUBEFLOW_TPU_SLO_ERROR_BUDGET}={budget}: want <= 1.0"
        )
    objectives = default_objectives(
        ttft_p95_s=_positive(KUBEFLOW_TPU_SLO_TTFT_P95_MS, 500.0) / 1000.0,
        inter_token_p95_s=_positive(
            KUBEFLOW_TPU_SLO_INTER_TOKEN_P95_MS, 200.0
        ) / 1000.0,
        queue_wait_p95_s=_positive(
            KUBEFLOW_TPU_SLO_QUEUE_WAIT_P95_MS, 250.0
        ) / 1000.0,
        budget=budget,
    )
    engine_kwargs = {
        "fast_burn": _positive(KUBEFLOW_TPU_SLO_FAST_BURN, 14.4),
        "slow_burn": _positive(KUBEFLOW_TPU_SLO_SLOW_BURN, 2.0),
    }
    return objectives, engine_kwargs
