"""Windowed fleet telemetry signals: the autoscaler's input contract.

PR 10 made individual requests traceable; this module makes the fleet's
behavior *over time* queryable. Everything the stack already measures —
/stats counters, span-derived request latencies, the flight recorder's
stall ledger — exists only as instantaneous numbers; a control loop
(ROADMAP item 2's trace-driven autoscaler) needs rates, rolling
quantiles, and per-tenant breakdowns over a bounded recent horizon.

Design: a fixed ring of ALIGNED time windows (``windows`` × ``window_s``,
e.g. 180×10s = a 30-minute horizon). Window index is ``now // window_s``,
so two series with the same clock agree on window boundaries, and an
idle series costs nothing — a slot is lazily reset when its epoch comes
around again. The clock is injected, so every behavior here is
fake-clock testable (tests/test_signals.py).

Three series kinds, all registered on demand in a :class:`SignalHub`:

- **counter**: per-window sums + lifetime total → ``rate()`` converts to
  events/sec over any horizon (missing windows count as zero);
- **gauge**: last value per window → ``windows_over()`` answers "in how
  many recent windows did this exceed X" (the queue-wait SLO shape);
- **histogram**: per-window bounded sample reservoirs, merged and sorted
  at query time → streaming ``quantile()`` with exact small-N behavior
  (the smoke-scale TTFT-p95 agreement gate depends on that exactness).
  Past ``samples_per_window`` the reservoir keeps the most recent
  samples (ring overwrite) — deterministic, biased toward recency,
  which is what an alerting window wants.

On top, :class:`FleetTelemetry` is the gateway-side aggregator: it
ingests each replica's ``/stats`` scrape (counter deltas + gauges), the
gateway's own router events (requests/shed/reroutes per tenant, bounded
by :class:`TenantBuckets` top-K + ``other``), and relay-measured TTFT /
inter-token latencies, and serves the ``SignalSnapshot`` dict behind
``/debug/signals``. Construction is env-gated (``signals_from_env``):
with ``KUBEFLOW_TPU_SIGNALS_*`` unset the gateway carries a ``None`` and
the hot path stays exactly as fast as PR 10.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

TENANT_OTHER = "other"


class TenantBuckets:
    """Bounded-cardinality tenant labels: the first ``top_k`` distinct
    tenants keep their own bucket, everyone later folds into ``other``.
    First-come is deliberate — a stable assignment that never re-labels
    an existing series mid-flight (a popularity-ranked top-K would), and
    the fleet's long-lived tenants are exactly the early ones."""

    def __init__(self, top_k: int = 8):
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        self.top_k = top_k
        self._named: dict = {}
        self._lock = threading.Lock()

    def bucket(self, tenant: str) -> str:
        tenant = str(tenant)
        with self._lock:
            got = self._named.get(tenant)
            if got is not None:
                return got
            label = tenant if len(self._named) < self.top_k else TENANT_OTHER
            self._named[tenant] = label
            return label

    def buckets(self) -> list:
        """Every label currently in use (top-K names + maybe 'other')."""
        with self._lock:
            return sorted(set(self._named.values()))


class _Series:
    """Ring of aligned windows. Not thread-safe — the hub locks."""

    __slots__ = ("window_s", "windows", "_slots")

    def __init__(self, window_s: float, windows: int):
        self.window_s = window_s
        self.windows = windows
        self._slots: list = [None] * windows  # (epoch, payload)

    def _fresh(self):
        raise NotImplementedError

    def _slot(self, now: float):
        """Payload of the current window, resetting a stale ring slot."""
        epoch = int(now // self.window_s)
        i = epoch % self.windows
        slot = self._slots[i]
        if slot is None or slot[0] != epoch:
            slot = (epoch, self._fresh())
            self._slots[i] = slot
        return slot[1]

    def _live(self, over_s: float, now: float) -> list:
        """Payloads of the windows covering the last ``over_s`` seconds
        (current partial window included — an alert must see the most
        recent events, not wait a full window for them)."""
        epoch = int(now // self.window_s)
        k = min(self.windows, max(1, -(-int(over_s * 1000) // int(self.window_s * 1000))))
        out = []
        for e in range(epoch - k + 1, epoch + 1):
            slot = self._slots[e % self.windows]
            if slot is not None and slot[0] == e:
                out.append(slot[1])
        return out


class CounterSeries(_Series):
    __slots__ = ("total",)

    def __init__(self, window_s: float, windows: int):
        super().__init__(window_s, windows)
        self.total = 0.0

    def _fresh(self):
        return [0.0]

    def inc(self, now: float, value: float = 1.0) -> None:
        self._slot(now)[0] += value
        self.total += value

    def sum_over(self, over_s: float, now: float) -> float:
        return sum(w[0] for w in self._live(over_s, now))

    def rate(self, over_s: float, now: float) -> float:
        """Events/sec over the horizon. The denominator is the full
        requested span (missing windows were genuinely idle, not
        unknown), clamped to the ring's reach."""
        span = min(over_s, self.window_s * self.windows)
        return self.sum_over(over_s, now) / span if span > 0 else 0.0


class GaugeSeries(_Series):
    __slots__ = ("last",)

    def __init__(self, window_s: float, windows: int):
        super().__init__(window_s, windows)
        self.last: Optional[float] = None

    def _fresh(self):
        return [None]

    def set(self, now: float, value: float) -> None:
        self._slot(now)[0] = value
        self.last = value

    def windows_over(self, threshold: float, over_s: float,
                     now: float) -> tuple:
        """(windows where the gauge exceeded threshold, windows with any
        observation) over the horizon — the 'bad minutes' SLO shape."""
        vals = [w[0] for w in self._live(over_s, now) if w[0] is not None]
        return sum(1 for v in vals if v > threshold), len(vals)


class HistogramSeries(_Series):
    __slots__ = ("cap", "count")

    def __init__(self, window_s: float, windows: int, cap: int = 256):
        super().__init__(window_s, windows)
        self.cap = cap
        self.count = 0  # lifetime observations

    def _fresh(self):
        return {"n": 0, "samples": []}

    def observe(self, now: float, value: float) -> None:
        w = self._slot(now)
        if len(w["samples"]) < self.cap:
            w["samples"].append(value)
        else:
            w["samples"][w["n"] % self.cap] = value
        w["n"] += 1
        self.count += 1

    def merged(self, over_s: float, now: float) -> list:
        out: list = []
        for w in self._live(over_s, now):
            out.extend(w["samples"])
        out.sort()
        return out

    def events(self, over_s: float, now: float) -> int:
        """TRUE observation count over the horizon (reservoirs may hold
        fewer) — the min-events guard must see real traffic volume."""
        return sum(w["n"] for w in self._live(over_s, now))

    def quantile(self, q: float, over_s: float, now: float):
        xs = self.merged(over_s, now)
        if not xs:
            return None
        n = len(xs)
        return xs[min(n - 1, max(0, -(-int(q * 1000) * n // 1000) - 1))]

    def fraction_over(self, threshold: float, over_s: float,
                      now: float) -> tuple:
        """(fraction of held samples over threshold, held sample count).
        Computed over the reservoirs, so it is an estimate past the
        per-window cap — documented bias toward recent samples."""
        xs = self.merged(over_s, now)
        if not xs:
            return 0.0, 0
        bad = sum(1 for v in xs if v > threshold)
        return bad / len(xs), len(xs)


class SignalHub:
    """Named series registry with one lock and one clock.

    Series are keyed ``(name, child)`` — ``child=None`` is the
    aggregate; callers use children for per-tenant or per-replica
    breakdowns (cardinality is the CALLER's contract: tenants come
    pre-bucketed through TenantBuckets, replica children are bounded by
    the ring size). All record/query methods are thread-safe.
    """

    def __init__(self, window_s: float = 10.0, windows: int = 12,
                 clock: Optional[Callable[[], float]] = None,
                 samples_per_window: int = 256):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if windows < 2:
            raise ValueError(f"windows must be >= 2, got {windows}")
        if samples_per_window < 1:
            raise ValueError(
                f"samples_per_window must be >= 1, got {samples_per_window}"
            )
        self.window_s = float(window_s)
        self.windows = int(windows)
        self.samples_per_window = int(samples_per_window)
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._histograms: dict = {}

    def span_s(self) -> float:
        """The horizon the ring can answer about."""
        return self.window_s * self.windows

    def _now(self, now: Optional[float]) -> float:
        return self.clock() if now is None else now

    # -- recording ---------------------------------------------------------

    def inc(self, name: str, value: float = 1.0, child: Optional[str] = None,
            now: Optional[float] = None) -> None:
        now = self._now(now)
        with self._lock:
            s = self._counters.get((name, child))
            if s is None:
                s = self._counters[(name, child)] = CounterSeries(
                    self.window_s, self.windows
                )
            s.inc(now, value)

    def set_gauge(self, name: str, value: float,
                  child: Optional[str] = None,
                  now: Optional[float] = None) -> None:
        now = self._now(now)
        with self._lock:
            s = self._gauges.get((name, child))
            if s is None:
                s = self._gauges[(name, child)] = GaugeSeries(
                    self.window_s, self.windows
                )
            s.set(now, value)

    def observe(self, name: str, value: float, child: Optional[str] = None,
                now: Optional[float] = None) -> None:
        now = self._now(now)
        with self._lock:
            s = self._histograms.get((name, child))
            if s is None:
                s = self._histograms[(name, child)] = HistogramSeries(
                    self.window_s, self.windows, self.samples_per_window
                )
            s.observe(now, value)

    # -- queries -----------------------------------------------------------

    def rate(self, name: str, over_s: Optional[float] = None,
             child: Optional[str] = None,
             now: Optional[float] = None) -> float:
        now, over_s = self._now(now), over_s or self.span_s()
        with self._lock:
            s = self._counters.get((name, child))
            return s.rate(over_s, now) if s else 0.0

    def counter_sum(self, name: str, over_s: Optional[float] = None,
                    child: Optional[str] = None,
                    now: Optional[float] = None) -> float:
        now, over_s = self._now(now), over_s or self.span_s()
        with self._lock:
            s = self._counters.get((name, child))
            return s.sum_over(over_s, now) if s else 0.0

    def counter_total(self, name: str,
                      child: Optional[str] = None) -> float:
        with self._lock:
            s = self._counters.get((name, child))
            return s.total if s else 0.0

    def gauge_last(self, name: str, child: Optional[str] = None):
        with self._lock:
            s = self._gauges.get((name, child))
            return s.last if s else None

    def gauge_children(self, name: str) -> dict:
        with self._lock:
            return {
                child: s.last
                for (n, child), s in self._gauges.items()
                if n == name and child is not None and s.last is not None
            }

    def gauge_windows_over(self, name: str, threshold: float,
                           over_s: Optional[float] = None,
                           now: Optional[float] = None) -> tuple:
        """(bad, observed) windows across the aggregate AND every child
        of ``name`` — for a fleet gauge like per-replica queue wait, a
        window is bad when ANY replica exceeded the threshold."""
        now, over_s = self._now(now), over_s or self.span_s()
        bad = total = 0
        with self._lock:
            for (n, _child), s in self._gauges.items():
                if n != name:
                    continue
                b, t = s.windows_over(threshold, over_s, now)
                bad += b
                total += t
        return bad, total

    def quantile(self, name: str, q: float, over_s: Optional[float] = None,
                 child: Optional[str] = None, now: Optional[float] = None):
        now, over_s = self._now(now), over_s or self.span_s()
        with self._lock:
            s = self._histograms.get((name, child))
            return s.quantile(q, over_s, now) if s else None

    def fraction_over(self, name: str, threshold: float,
                      over_s: Optional[float] = None,
                      child: Optional[str] = None,
                      now: Optional[float] = None) -> tuple:
        now, over_s = self._now(now), over_s or self.span_s()
        with self._lock:
            s = self._histograms.get((name, child))
            return s.fraction_over(threshold, over_s, now) if s else (0.0, 0)

    def event_count(self, name: str, over_s: Optional[float] = None,
                    child: Optional[str] = None,
                    now: Optional[float] = None) -> int:
        now, over_s = self._now(now), over_s or self.span_s()
        with self._lock:
            s = self._histograms.get((name, child))
            return s.events(over_s, now) if s else 0

    def counter_children(self, name: str) -> list:
        with self._lock:
            return sorted(
                child for (n, child) in self._counters
                if n == name and child is not None
            )

    def histogram_children(self, name: str) -> list:
        with self._lock:
            return sorted(
                child for (n, child) in self._histograms
                if n == name and child is not None
            )


@dataclass(frozen=True)
class SignalsConfig:
    """Telemetry-plane shape: window size, ring length (the horizon must
    cover the SLO engine's slow window), tenant label cardinality."""

    window_s: float = 10.0
    windows: int = 180          # 30-minute horizon at 10s windows
    top_k_tenants: int = 8


def signals_from_env() -> Optional[SignalsConfig]:
    """None unless KUBEFLOW_TPU_SIGNALS_ENABLE opts in (the telemetry
    plane must be a hot-path no-op by default). Raises on garbage — a
    hand-set env var must not silently fall back to defaults."""
    import os

    from kubeflow_tpu.webhook.tpu_env import (
        KUBEFLOW_TPU_SIGNALS_ENABLE,
        KUBEFLOW_TPU_SIGNALS_TENANTS,
        KUBEFLOW_TPU_SIGNALS_WINDOW_S,
        KUBEFLOW_TPU_SIGNALS_WINDOWS,
    )

    raw = os.environ.get(KUBEFLOW_TPU_SIGNALS_ENABLE, "").strip().lower()
    if raw not in ("", "0", "false", "1", "true"):
        raise ValueError(
            f"{KUBEFLOW_TPU_SIGNALS_ENABLE}={raw!r}: want 0/1/true/false"
        )
    if raw not in ("1", "true"):
        return None
    defaults = SignalsConfig()

    def _num(name, default, minimum, cast):
        value = os.environ.get(name, "").strip()
        if not value:
            return default
        try:
            got = cast(value)
        except ValueError:
            got = minimum - 1
        if got < minimum:
            raise ValueError(f"{name}={value!r}: want a number >= {minimum}")
        return got

    return SignalsConfig(
        window_s=float(
            _num(KUBEFLOW_TPU_SIGNALS_WINDOW_S, defaults.window_s, 1, float)
        ),
        windows=_num(KUBEFLOW_TPU_SIGNALS_WINDOWS, defaults.windows, 2, int),
        top_k_tenants=_num(
            KUBEFLOW_TPU_SIGNALS_TENANTS, defaults.top_k_tenants, 1, int
        ),
    )


class FleetTelemetry:
    """Gateway-side signal plane: hub + tenant buckets + SLO engine.

    Feeds (all no-ops for the gateway when this object is None):

    - router events: ``observe_request`` / ``observe_shed`` /
      ``observe_reroute`` from the admission and relay paths, with TTFT
      and inter-token gaps measured AT THE RELAY (arrival → first SSE
      data line), so the numbers are what a client actually saw through
      the gateway, per tenant;
    - replica scrapes: ``ingest_replica`` turns each /stats payload into
      per-replica gauges and fleet counter DELTAS (cumulative counters
      re-based per endpoint; a replica restart resets its base instead
      of producing a negative spike).

    ``snapshot()`` is the SignalSnapshot contract ``/debug/signals``
    serves and the future autoscaler consumes; ``evaluate_slo()`` runs
    the burn-rate engine (the gateway's probe loop calls it every pass).
    """

    def __init__(self, config: Optional[SignalsConfig] = None, *,
                 objectives=None, metrics=None,
                 clock: Optional[Callable[[], float]] = None,
                 slo_options: Optional[dict] = None):
        from kubeflow_tpu.observability.slo import (
            SLOEngine,
            default_objectives,
        )

        self.config = config or SignalsConfig()
        self.clock = clock or time.monotonic
        self.hub = SignalHub(
            window_s=self.config.window_s, windows=self.config.windows,
            clock=self.clock,
        )
        self.tenants = TenantBuckets(self.config.top_k_tenants)
        self.slo = SLOEngine(
            self.hub,
            objectives if objectives is not None else default_objectives(),
            clock=self.clock, metrics=metrics, **(slo_options or {}),
        )
        self._scrape_lock = threading.Lock()
        self._replica_base: dict = {}  # endpoint -> {stat: last cumulative}
        # endpoint -> clock() of the last *fresh* /stats ingest. The
        # autoscaler's staleness freeze reads this: a replica whose
        # scrape age grows past its threshold means the control loop is
        # flying blind and must hold capacity rather than act.
        self._replica_last_scrape: dict = {}

    @classmethod
    def from_env(cls, metrics=None,
                 clock: Optional[Callable[[], float]] = None
                 ) -> Optional["FleetTelemetry"]:
        config = signals_from_env()
        if config is None:
            return None
        from kubeflow_tpu.observability.slo import slo_from_env

        objectives, slo_options = slo_from_env()
        return cls(config, objectives=objectives, metrics=metrics,
                   clock=clock, slo_options=slo_options)

    # -- router-side feeds -------------------------------------------------

    def observe_request(self, tenant: str, ok: bool,
                        ttft_s: Optional[float] = None,
                        inter_token=None,
                        e2e_s: Optional[float] = None) -> None:
        bucket = self.tenants.bucket(tenant)
        hub = self.hub
        hub.inc("requests")
        hub.inc("requests", child=bucket)
        if not ok:
            hub.inc("errors")
            hub.inc("errors", child=bucket)
            hub.inc("bad_requests")
        if ttft_s is not None:
            hub.observe("ttft_s", ttft_s)
            hub.observe("ttft_s", ttft_s, child=bucket)
        for gap in inter_token or ():
            hub.observe("inter_token_s", gap)
        if e2e_s is not None:
            hub.observe("request_s", e2e_s)

    def observe_shed(self, tenant: str) -> None:
        bucket = self.tenants.bucket(tenant)
        hub = self.hub
        hub.inc("requests")
        hub.inc("requests", child=bucket)
        hub.inc("shed")
        hub.inc("shed", child=bucket)
        hub.inc("bad_requests")

    def observe_reroute(self) -> None:
        self.hub.inc("reroutes")

    def observe_kv_transfer(self, nbytes: int, latency_s: float,
                            ok: bool = True) -> None:
        """One prefill→decode paged-KV handoff attempt (disaggregated
        serving): byte volume + hop latency, failures counted separately
        so /debug/signals can show the fallback rate next to the
        transfer rate."""
        hub = self.hub
        if ok:
            hub.inc("kv_transfers")
            hub.inc("kv_transfer_bytes", float(nbytes))
            hub.observe("kv_transfer_s", latency_s)
        else:
            hub.inc("kv_transfer_failures")

    def observe_kv_peer_fetch(self, nbytes: int, latency_s: float,
                              ok: bool = True) -> None:
        """One fleet-KV-tier peer prefix fetch attempt: byte volume +
        whole-fetch latency, failures counted separately so
        /debug/signals shows the degrade-to-re-prefill rate next to the
        fetch rate."""
        hub = self.hub
        if ok:
            hub.inc("kv_peer_fetches")
            hub.inc("kv_peer_bytes", float(nbytes))
            hub.observe("kv_peer_fetch_s", latency_s)
        else:
            hub.inc("kv_peer_fetch_failures")

    def ingest_ring(self, size: int) -> None:
        self.hub.set_gauge("ring_size", float(size))

    # -- replica-scrape feed -----------------------------------------------

    _REPLICA_COUNTERS = (
        ("served", "fleet_served"),
        ("requests_shed", "fleet_replica_shed"),
        ("tokens_generated", "fleet_tokens"),
        ("engine_step_stalls", "fleet_stalls"),
        # HBM economy: host-RAM swap-tier traffic. These live in the
        # nested /stats ``kv_swap`` block — a dotted path descends one
        # level per segment.
        ("kv_swap.swap_out", "fleet_kv_swap_out"),
        ("kv_swap.swap_in", "fleet_kv_swap_in"),
        ("kv_swap.restored_tokens", "fleet_kv_swap_restored_tokens"),
        # Speculative decoding: accepted proposals + verify rounds from
        # the /stats ``speculative`` block → fleet acceptance rates.
        ("speculative.accepted", "fleet_spec_accept"),
        ("speculative.rounds", "fleet_spec_rounds"),
        # Multi-LoRA: hot-adapter cache churn from the ``lora_cache``
        # block — the (prefix, adapter) affinity router's scoreboard.
        ("lora_cache.hits", "fleet_lora_cache_hits"),
        ("lora_cache.misses", "fleet_lora_cache_misses"),
        ("lora_cache.evictions", "fleet_lora_cache_evictions"),
    )

    def ingest_replica(self, endpoint: str, stats: Optional[dict]) -> None:
        if not stats:
            return
        hub = self.hub

        def _gauge(name, value):
            if isinstance(value, (int, float)) and not isinstance(
                    value, bool):
                hub.set_gauge(name, float(value), child=endpoint)

        _gauge("replica_queue_depth", stats.get("queued"))
        _gauge("replica_active_slots", stats.get("active_slots"))
        _gauge("replica_queue_wait_p95_s",
               (stats.get("queue_wait_s") or {}).get("p95"))
        _gauge("replica_inter_token_p95_s",
               (stats.get("inter_token_s") or {}).get("p95"))
        _gauge("replica_batch_fill",
               (stats.get("ragged") or {}).get("batch_fill"))
        _gauge("replica_prefix_hit_ratio",
               (stats.get("prefix_cache") or {}).get("hit_ratio"))
        _gauge("replica_kv_swap_bytes",
               (stats.get("kv_swap") or {}).get("swap_bytes"))
        with self._scrape_lock:
            self._replica_last_scrape[endpoint] = self.clock()
            base = self._replica_base.setdefault(endpoint, {})
            for stat, signal in self._REPLICA_COUNTERS:
                cur: object = stats
                for part in stat.split("."):
                    cur = cur.get(part) if isinstance(cur, dict) else None
                if not isinstance(cur, (int, float)) or isinstance(
                        cur, bool):
                    continue
                prev = base.get(stat)
                base[stat] = cur
                if prev is None:
                    continue  # first sight: establish the base only
                # A restarted replica's cumulative counter rebased to ~0:
                # count its fresh total, never a negative delta.
                delta = cur - prev if cur >= prev else cur
                if delta:
                    hub.inc(signal, float(delta))

    def forget_replica(self, endpoint: str) -> None:
        """Drop the per-endpoint rebase state and scrape timestamp for a
        replica that left the fleet — a departed (drained + released)
        replica's growing scrape age must not freeze the autoscaler, and
        a later re-add re-establishes its counter base from scratch."""
        with self._scrape_lock:
            self._replica_base.pop(endpoint, None)
            self._replica_last_scrape.pop(endpoint, None)

    def scrape_ages(self, now: Optional[float] = None) -> dict:
        """Per-endpoint seconds since the last fresh /stats ingest."""
        now = self.clock() if now is None else now
        with self._scrape_lock:
            return {
                ep: max(0.0, now - t)
                for ep, t in self._replica_last_scrape.items()
            }

    # -- autoscaler feed ---------------------------------------------------

    _AUTOSCALE_ACTIONS = ("up", "down", "hold", "freeze")

    def observe_autoscale(self, action: str) -> None:
        """One autoscaler decision, windowed so /debug/signals shows
        scale churn next to the load signals that caused it."""
        if action not in self._AUTOSCALE_ACTIONS:
            raise ValueError(
                f"autoscale action must be one of "
                f"{self._AUTOSCALE_ACTIONS}, got {action!r}"
            )
        self.hub.inc(f"autoscale_{action}")

    # -- migration feed ----------------------------------------------------

    _MIGRATION_EVENTS = ("started", "completed", "fell_back")

    def observe_migration(self, event: str) -> None:
        """One live-migration lifecycle event (runtime/migration.py),
        windowed so /debug/signals shows migration churn next to the
        preemption and load signals that triggered it."""
        if event not in self._MIGRATION_EVENTS:
            raise ValueError(
                f"migration event must be one of "
                f"{self._MIGRATION_EVENTS}, got {event!r}"
            )
        self.hub.inc(f"migration_{event}")

    # -- outputs -----------------------------------------------------------

    def evaluate_slo(self, now: Optional[float] = None) -> dict:
        return self.slo.evaluate(now=now)

    def snapshot(self, over_s: Optional[float] = None,
                 now: Optional[float] = None) -> dict:
        """The SignalSnapshot contract: fleet aggregates + per-tenant
        breakdowns over ``over_s`` (default: the whole ring horizon)."""
        hub = self.hub
        now = self.clock() if now is None else now
        over_s = over_s or hub.span_s()

        def _hist(name):
            return {
                "p50": hub.quantile(name, 0.50, over_s, now=now),
                "p95": hub.quantile(name, 0.95, over_s, now=now),
                "count": hub.event_count(name, over_s, now=now),
            }

        def _rate(name):
            return round(hub.rate(name, over_s, now=now), 6)

        tenants = {}
        for bucket in self.tenants.buckets():
            tenants[bucket] = {
                "requests_per_s": round(
                    hub.rate("requests", over_s, child=bucket, now=now), 6
                ),
                "requests": hub.counter_sum(
                    "requests", over_s, child=bucket, now=now
                ),
                "shed": hub.counter_sum(
                    "shed", over_s, child=bucket, now=now
                ),
                "errors": hub.counter_sum(
                    "errors", over_s, child=bucket, now=now
                ),
                "ttft_p95_s": hub.quantile(
                    "ttft_s", 0.95, over_s, child=bucket, now=now
                ),
            }
        return {
            "enabled": True,
            "now": round(now, 3),
            "window_s": hub.window_s,
            "windows": hub.windows,
            "over_s": over_s,
            "fleet": {
                "ttft_s": _hist("ttft_s"),
                "inter_token_s": _hist("inter_token_s"),
                "request_s": _hist("request_s"),
                "requests_per_s": _rate("requests"),
                "errors_per_s": _rate("errors"),
                "shed_per_s": _rate("shed"),
                "reroutes_per_s": _rate("reroutes"),
                # Disaggregated serving: KV handoff volume + hop latency.
                "kv_transfers_per_s": _rate("kv_transfers"),
                "kv_transfer_failures_per_s": _rate("kv_transfer_failures"),
                "kv_transfer_bytes_per_s": _rate("kv_transfer_bytes"),
                "kv_transfer_s": _hist("kv_transfer_s"),
                # Fleet KV tier: peer prefix fetch volume + latency.
                "kv_peer_fetches_per_s": _rate("kv_peer_fetches"),
                "kv_peer_fetch_failures_per_s": _rate(
                    "kv_peer_fetch_failures"
                ),
                "kv_peer_bytes_per_s": _rate("kv_peer_bytes"),
                "kv_peer_fetch_s": _hist("kv_peer_fetch_s"),
                # HBM economy: swap-tier churn as windowed rates, plus
                # the per-replica resident swap bytes.
                "kv_swap_out_per_s": _rate("fleet_kv_swap_out"),
                "kv_swap_in_per_s": _rate("fleet_kv_swap_in"),
                "kv_swap_restored_tokens_per_s": _rate(
                    "fleet_kv_swap_restored_tokens"
                ),
                "replica_kv_swap_bytes": hub.gauge_children(
                    "replica_kv_swap_bytes"
                ),
                "served_per_s": _rate("fleet_served"),
                "tokens_per_s": _rate("fleet_tokens"),
                "stalls_per_s": _rate("fleet_stalls"),
                # Speculative decoding + multi-LoRA serving rates.
                "spec_accept_per_s": _rate("fleet_spec_accept"),
                "spec_rounds_per_s": _rate("fleet_spec_rounds"),
                "lora_cache_hits_per_s": _rate("fleet_lora_cache_hits"),
                "lora_cache_misses_per_s": _rate(
                    "fleet_lora_cache_misses"
                ),
                "lora_cache_evictions_per_s": _rate(
                    "fleet_lora_cache_evictions"
                ),
                "ring_size": hub.gauge_last("ring_size"),
                "replica_queue_depth": hub.gauge_children(
                    "replica_queue_depth"
                ),
                "replica_queue_wait_p95_s": hub.gauge_children(
                    "replica_queue_wait_p95_s"
                ),
                "replica_batch_fill": hub.gauge_children(
                    "replica_batch_fill"
                ),
                "replica_prefix_hit_ratio": hub.gauge_children(
                    "replica_prefix_hit_ratio"
                ),
                # Staleness signal for the autoscaler freeze: seconds
                # since each replica's last fresh /stats ingest.
                "last_scrape_age_s": {
                    ep: round(age, 3)
                    for ep, age in sorted(self.scrape_ages(now=now).items())
                },
                # Autoscaler decision churn, windowed like every other
                # fleet rate so ramps and their scale actions line up.
                "autoscale_up_per_s": _rate("autoscale_up"),
                "autoscale_down_per_s": _rate("autoscale_down"),
                "autoscale_hold_per_s": _rate("autoscale_hold"),
                "autoscale_freeze_per_s": _rate("autoscale_freeze"),
                # Live-migration churn: starts vs completions vs ladder
                # fallbacks, windowed like the preemption signals above.
                "migration_started_per_s": _rate("migration_started"),
                "migration_completed_per_s": _rate("migration_completed"),
                "migration_fell_back_per_s": _rate("migration_fell_back"),
            },
            "tenants": tenants,
        }
