"""In-repo distributed tracing: spans, W3C traceparent, exporters.

Grown from the webhook-admission stub (reference parity: the ODH mutating
webhook's lazily acquired tracer, one root span per admission, a child span
inside maybeRestartRunningNotebook — notebook_mutating_webhook.go:74-76,
:368-373, :526) into the tracing layer for the whole request path:
gateway route → replica server → batcher admission → ragged engine dispatch,
plus controller reconcile, the preemption recovery ladder, and checkpoint
save/restore.

Shape (OTel-like, zero dependencies):

- ``Span`` carries ``trace_id``/``span_id``/``parent_id`` (W3C hex) and is
  BOTH a context manager and manually endable via ``.end()``.
- ``Tracer.start_span`` parents onto the contextvar-tracked current span
  (thread- and task-safe, unlike the old module-global stack) and installs
  the new span as current until it ends.
- ``Tracer.begin_span`` creates a span WITHOUT installing it as current —
  for spans that start in one thread and end in another (e.g. the server's
  queue-wait span starts in the HTTP handler thread and ends when the
  engine's admission loop picks the request up).
- ``format_traceparent`` / ``parse_traceparent`` implement the W3C
  ``00-<trace_id>-<span_id>-<flags>`` header carried on the gateway→replica
  HTTP hop.
- Sampling is deterministic in the trace id (``deterministic_sample``), so
  every hop of one request agrees on the decision without coordination.
- Exporters: ``InMemoryExporter`` (tests), ``RingBufferExporter`` (bounded,
  backs the ``/debug/traces`` endpoint), ``JSONLExporter`` (file export,
  gated by ``KUBEFLOW_TPU_TRACE_EXPORT``).

Production default stays the no-op global provider; ``configure_from_env``
installs a recording provider only when a ``KUBEFLOW_TPU_TRACE_*`` variable
is set, so test-installed providers are never clobbered.
"""

from __future__ import annotations

import contextvars
import json
import os
import re
import secrets
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional


def new_trace_id() -> str:
    return secrets.token_hex(16)


def new_span_id() -> str:
    return secrets.token_hex(8)


_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def format_traceparent(span: "Span") -> str:
    """W3C traceparent for ``span``; empty string for the no-op span (no
    identity to propagate)."""
    if not span.trace_id:
        return ""
    flags = "00" if isinstance(span, _NoopSpan) else "01"
    return f"00-{span.trace_id}-{span.span_id}-{flags}"


def parse_traceparent(header: Optional[str]):
    """Parse a W3C traceparent header.

    Returns ``(trace_id, parent_span_id, sampled)`` or None for a missing /
    malformed header (malformed headers are dropped, not propagated — the
    receiver starts a fresh trace, per the W3C spec's restart rule).
    """
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if not m:
        return None
    trace_id, span_id, flags = m.groups()
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, bool(int(flags, 16) & 0x01)


def deterministic_sample(trace_id: str, rate: float) -> bool:
    """Head-sampling decision as a pure function of the trace id: every
    component of a distributed trace reaches the same verdict with no
    coordination (the gateway's decision rides the traceparent flags, but a
    replica hit directly still agrees)."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return int(trace_id[:8], 16) < rate * 0x1_0000_0000


@dataclass
class Span:
    name: str
    attributes: dict = field(default_factory=dict)
    events: list = field(default_factory=list)
    parent: Optional["Span"] = None
    start_time: float = 0.0
    end_time: float = 0.0
    status: str = "OK"  # OK | ERROR
    status_message: str = ""
    trace_id: str = ""
    span_id: str = ""
    parent_id: str = ""  # parent span id, incl. remote (traceparent) parents
    _provider: Optional["TracerProvider"] = field(
        default=None, repr=False, compare=False
    )
    _token: Optional[contextvars.Token] = field(
        default=None, repr=False, compare=False
    )
    _ended: bool = field(default=False, repr=False, compare=False)

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, attributes: Optional[dict] = None) -> None:
        self.events.append({"name": name, "attributes": attributes or {}})

    def record_error(self, err: Exception) -> None:
        self.status = "ERROR"
        self.status_message = str(err)

    def end(self) -> None:
        """Idempotent; safe from a different thread than the starter (the
        context slot is then restored by value rather than by token)."""
        if self._ended:
            return
        self._ended = True
        self.end_time = time.time()
        self._restore_context()
        if self._provider is not None:
            self._provider._export(self)

    def _restore_context(self) -> None:
        if self._token is None:
            return
        token, self._token = self._token, None
        try:
            _current.reset(token)
        except ValueError:
            # Token minted in another context (cross-thread end): fall back
            # to re-pointing at the parent.
            _current.set(self.parent)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if isinstance(exc, Exception):
            self.record_error(exc)
        self.end()
        return False

    @property
    def duration_s(self) -> float:
        return max(0.0, self.end_time - self.start_time)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "duration_ms": round(self.duration_s * 1e3, 3),
            "status": self.status,
            "status_message": self.status_message,
            "attributes": self.attributes,
            "events": self.events,
        }


class _NoopSpan(Span):
    """Recording methods are no-ops; attribute writes go nowhere. Unsampled
    spans are fresh _NoopSpan instances that still carry a trace id, so
    propagation (traceparent, X-Request-Id) survives the sampling decision."""

    def set_attribute(self, key: str, value) -> None:
        pass

    def add_event(self, name: str, attributes: Optional[dict] = None) -> None:
        pass

    def record_error(self, err: Exception) -> None:
        pass

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        self._restore_context()


_NOOP_SPAN = _NoopSpan(name="noop")

# Current-span context (replaces the old module-global ``_active_spans``
# stack, which was shared across threads — the serving path traces from
# HTTP handler threads and the engine drive thread concurrently).
_current: contextvars.ContextVar[Optional[Span]] = contextvars.ContextVar(
    "kubeflow_tpu_current_span", default=None
)


def current_span() -> Span:
    """This thread's (context's) active span. Never None: callers get the
    no-op singleton when nothing is active, so instrumentation sites can
    add events/attributes unconditionally."""
    return _current.get() or _NOOP_SPAN


class InMemoryExporter:
    """Collects ended spans (test analog of the reference's tracetest
    in-memory exporter)."""

    def __init__(self):
        self.spans: list[Span] = []

    def export(self, span: Span) -> None:
        self.spans.append(span)

    def by_name(self, name: str) -> list[Span]:
        return [s for s in self.spans if s.name == name]

    def reset(self) -> None:
        self.spans.clear()


class RingBufferExporter:
    """Bounded in-memory ring of the most recent finished spans; backs the
    serving components' ``/debug/traces`` endpoint. Eviction is oldest-first
    at ``capacity`` spans."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(1, int(capacity))
        self._spans: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [s.to_dict() for s in self._spans]

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


class JSONLExporter:
    """Appends one JSON object per finished span to ``path``. Writes are
    lock-serialized and the file is opened per export, so concurrent handler
    threads and late process exit never interleave or truncate records."""

    def __init__(self, path):
        self.path = str(path)
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        line = json.dumps(span.to_dict(), sort_keys=True, default=str)
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line + "\n")


class Tracer:
    def __init__(self, name: str, provider: "TracerProvider"):
        self.name = name
        self.provider = provider

    @property
    def exporter(self):
        return self.provider.exporter

    def start_span(
        self,
        name: str,
        parent: Optional[Span] = None,
        traceparent: Optional[str] = None,
        **attributes,
    ) -> Span:
        """Create a span, install it as the contextvar-current span, and
        return it. The result is a context manager (``with ... as span:``)
        AND manually endable (``span.end()``); ``with`` is the norm — the
        span-unended lint rule flags start_span results that are neither
        with-managed nor ended in a finally."""
        return self._make(name, parent, traceparent, attributes, install=True)

    def begin_span(
        self,
        name: str,
        parent: Optional[Span] = None,
        traceparent: Optional[str] = None,
        **attributes,
    ) -> Span:
        """Like start_span but does NOT become the contextvar-current span:
        for spans handed across threads (started here, ``.end()``-ed
        elsewhere), where installing into this thread's context would leak."""
        return self._make(name, parent, traceparent, attributes, install=False)

    def _make(self, name, parent, traceparent, attributes, install) -> Span:
        if not self.provider.recording:
            return _NOOP_SPAN
        if parent is None:
            parent = _current.get()
        remote = parse_traceparent(traceparent) if parent is None else None
        if parent is not None:
            trace_id = parent.trace_id or new_trace_id()
            parent_id = parent.span_id
            sampled = not isinstance(parent, _NoopSpan)
        elif remote is not None:
            trace_id, parent_id, sampled = remote
            sampled = sampled and deterministic_sample(
                trace_id, self.provider.sample_rate
            )
        else:
            trace_id = new_trace_id()
            parent_id = ""
            sampled = deterministic_sample(trace_id, self.provider.sample_rate)
        cls = Span if sampled else _NoopSpan
        span = cls(
            name=name,
            attributes=dict(attributes),
            parent=parent if isinstance(parent, Span) else None,
            start_time=time.time(),
            trace_id=trace_id,
            span_id=new_span_id(),
            parent_id=parent_id,
            _provider=self.provider if sampled else None,
        )
        if install:
            span._token = _current.set(span)
        return span


class TracerProvider:
    """Global provider; the default exports nowhere (OTel's no-op global).

    ``TracerProvider(exporter)`` keeps the original single-exporter calling
    convention; ``exporters=[...]`` fans each finished span out to several
    (ring buffer + JSONL file in the env-configured production shape).
    """

    def __init__(
        self,
        exporter=None,
        *,
        exporters=None,
        sample_rate: float = 1.0,
    ):
        self.exporters = ([exporter] if exporter is not None else []) + list(
            exporters or []
        )
        self.sample_rate = float(sample_rate)

    @property
    def exporter(self):
        return self.exporters[0] if self.exporters else None

    @property
    def recording(self) -> bool:
        return bool(self.exporters)

    def _export(self, span: Span) -> None:
        for exp in self.exporters:
            exp.export(span)

    def ring(self) -> Optional[RingBufferExporter]:
        for exp in self.exporters:
            if isinstance(exp, RingBufferExporter):
                return exp
        return None

    def get_tracer(self, name: str) -> Tracer:
        return Tracer(name, self)


_provider = TracerProvider()


def set_tracer_provider(provider: TracerProvider) -> None:
    global _provider
    _provider = provider


def get_tracer(name: str) -> Tracer:
    """Lazy tracer acquisition (reference getWebhookTracer :74-76): always
    reads the *current* global provider, so a provider installed after
    import is picked up."""
    return _provider.get_tracer(name)


def enabled() -> bool:
    """Cheap guard for per-step instrumentation: False under the default
    no-op provider, so the hot engine loop skips span construction."""
    return _provider.recording


def trace_ring() -> Optional[RingBufferExporter]:
    """The installed provider's ring buffer (``/debug/traces`` source)."""
    return _provider.ring()


def configure_from_env() -> bool:
    """Install a recording provider from ``KUBEFLOW_TPU_TRACE_*`` env.

    No-op (returns False) when none of the variables are set OR a recording
    provider is already installed — serving entrypoints call this from
    their constructors, and it must never clobber a provider a test (or an
    earlier component in the same process) installed.
    """
    from kubeflow_tpu.webhook.tpu_env import (
        KUBEFLOW_TPU_TRACE_EXPORT,
        KUBEFLOW_TPU_TRACE_RING,
        KUBEFLOW_TPU_TRACE_SAMPLE,
    )

    export_path = os.environ.get(KUBEFLOW_TPU_TRACE_EXPORT, "")
    sample = os.environ.get(KUBEFLOW_TPU_TRACE_SAMPLE, "")
    ring = os.environ.get(KUBEFLOW_TPU_TRACE_RING, "")
    if not (export_path or sample or ring):
        return False
    if _provider.recording:
        return False
    try:
        capacity = int(ring) if ring else 512
    except ValueError as err:
        raise ValueError(f"{KUBEFLOW_TPU_TRACE_RING}={ring!r}: {err}") from err
    try:
        rate = float(sample) if sample else 1.0
    except ValueError as err:
        raise ValueError(
            f"{KUBEFLOW_TPU_TRACE_SAMPLE}={sample!r}: {err}"
        ) from err
    exporters: list = [RingBufferExporter(capacity)]
    if export_path:
        exporters.append(JSONLExporter(export_path))
    set_tracer_provider(
        TracerProvider(exporters=exporters, sample_rate=rate)
    )
    return True
