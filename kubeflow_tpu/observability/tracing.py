"""OpenTelemetry-shaped tracing for the admission path.

Reference parity: the ODH mutating webhook is the only traced component —
a lazily acquired tracer (reference components/odh-notebook-controller/
controllers/notebook_mutating_webhook.go:74-76 ``getWebhookTracer``), one
root span per admission with notebook/namespace/operation attributes
(:368-373), a child span inside maybeRestartRunningNotebook (:526), and
span events for imagestream-not-found (:912,:961). Production default is
the no-op global provider; tests install an in-memory exporter + real
provider (opentelemetry_test.go:26-50, wired in suite_test.go:104-108).

This module reproduces that shape without an OTel dependency: a global
``TracerProvider`` defaulting to no-op, ``set_tracer_provider`` to install
a recording one, and ``InMemoryExporter`` collecting finished spans.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional


@dataclass
class Span:
    name: str
    attributes: dict = field(default_factory=dict)
    events: list = field(default_factory=list)
    parent: Optional["Span"] = None
    start_time: float = 0.0
    end_time: float = 0.0
    status: str = "OK"  # OK | ERROR
    status_message: str = ""

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, attributes: Optional[dict] = None) -> None:
        self.events.append({"name": name, "attributes": attributes or {}})

    def record_error(self, err: Exception) -> None:
        self.status = "ERROR"
        self.status_message = str(err)


class _NoopSpan(Span):
    """Recording methods are no-ops; attribute writes go nowhere."""

    def set_attribute(self, key: str, value) -> None:
        pass

    def add_event(self, name: str, attributes: Optional[dict] = None) -> None:
        pass

    def record_error(self, err: Exception) -> None:
        pass


_NOOP_SPAN = _NoopSpan(name="noop")


class InMemoryExporter:
    """Collects ended spans (test analog of the reference's tracetest
    in-memory exporter)."""

    def __init__(self):
        self.spans: list[Span] = []

    def export(self, span: Span) -> None:
        self.spans.append(span)

    def by_name(self, name: str) -> list[Span]:
        return [s for s in self.spans if s.name == name]

    def reset(self) -> None:
        self.spans.clear()


# Active-span context, shared across Tracer instances (OTel context analog:
# the reference's child span in maybeRestartRunningNotebook parents onto the
# admission root span even though the tracer is re-acquired lazily).
_active_spans: list[Span] = []


class Tracer:
    def __init__(self, name: str, exporter: Optional[InMemoryExporter]):
        self.name = name
        self.exporter = exporter

    @contextlib.contextmanager
    def start_span(self, name: str, **attributes) -> Iterator[Span]:
        if self.exporter is None:
            yield _NOOP_SPAN
            return
        span = Span(
            name=name,
            attributes=dict(attributes),
            parent=_active_spans[-1] if _active_spans else None,
            start_time=time.time(),
        )
        _active_spans.append(span)
        try:
            yield span
        except Exception as err:
            span.record_error(err)
            raise
        finally:
            span.end_time = time.time()
            _active_spans.pop()
            self.exporter.export(span)


class TracerProvider:
    """Global provider; the default exports nowhere (OTel's no-op global)."""

    def __init__(self, exporter: Optional[InMemoryExporter] = None):
        self.exporter = exporter

    def get_tracer(self, name: str) -> Tracer:
        return Tracer(name, self.exporter)


_provider = TracerProvider()


def set_tracer_provider(provider: TracerProvider) -> None:
    global _provider
    _provider = provider


def get_tracer(name: str) -> Tracer:
    """Lazy tracer acquisition (reference getWebhookTracer :74-76): always
    reads the *current* global provider, so a provider installed after
    import is picked up."""
    return _provider.get_tracer(name)
