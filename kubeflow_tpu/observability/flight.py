"""Engine flight recorder: rolling step-time window with stall detection.

The per-step spans answer "where did THIS request's time go"; the flight
recorder answers "what has the engine been doing for the last N steps" —
cheap enough to stay on unconditionally (a deque append per step), so it is
populated even when tracing is sampled out or disabled. The serving layer
surfaces ``snapshot()`` under ``/stats`` and mirrors the stall count into
the ``tpu_engine_step_stall_total`` Prometheus counter.

Stall rule: a step is a stall when its duration exceeds ``stall_factor`` ×
the rolling median of the current window, once ``min_samples`` steps have
been observed (the guard keeps the first JAX compilations — orders of
magnitude slower than steady-state steps — from flagging every warm step
after them, and from being flagged against an empty window).

A stall can also trigger an on-device profile capture: set ``on_stall``
to a callback (the serving layer wires :class:`StallProfiler` in when
``KUBEFLOW_TPU_STALL_PROFILE_DIR`` is set) and the recorder invokes it
with the stall ledger entry — outside the recorder lock, so a slow
callback can never block the engine's next ``record_step``.
"""

from __future__ import annotations

import os
import pathlib
import statistics
import threading
import time
from collections import deque
from typing import Callable, Optional


class FlightRecorder:
    """Thread-compatible: the engine drive loop records; HTTP handler
    threads snapshot. A lock keeps the window and counters coherent."""

    def __init__(
        self,
        window: int = 256,
        stall_factor: float = 8.0,
        min_samples: int = 16,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.window = max(2, int(window))
        self.stall_factor = float(stall_factor)
        self.min_samples = max(2, int(min_samples))
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._durations: deque = deque(maxlen=self.window)
        self._fills: deque = deque(maxlen=self.window)
        self.steps = 0
        self.stalls = 0
        self.last_stall: Optional[dict] = None
        # Optional stall hook (e.g. StallProfiler.on_stall); called with
        # a copy of the ledger entry, outside the recorder lock.
        self.on_stall: Optional[Callable[[dict], object]] = None

    def record_step(
        self, duration_s: float, fill: Optional[float] = None
    ) -> bool:
        """Record one engine step; returns True when the step is a stall
        (caller attaches the span event / bumps the counter)."""
        with self._lock:
            stalled = False
            if len(self._durations) >= self.min_samples:
                median = statistics.median(self._durations)
                if median > 0 and duration_s > self.stall_factor * median:
                    stalled = True
                    self.stalls += 1
                    self.last_stall = {
                        "at": self.clock(),
                        "step": self.steps,
                        "duration_s": duration_s,
                        "median_s": median,
                        "factor": duration_s / median,
                    }
            self._durations.append(duration_s)
            if fill is not None:
                self._fills.append(fill)
            self.steps += 1
            info = dict(self.last_stall) if stalled else None
        if stalled and self.on_stall is not None:
            self.on_stall(info)
        return stalled

    def snapshot(self) -> dict:
        """Point-in-time view for ``/stats``: recent step-time distribution,
        fill, and the stall ledger."""
        with self._lock:
            durations = sorted(self._durations)
            n = len(durations)

            def pct(p: float) -> float:
                if not n:
                    return 0.0
                return durations[min(n - 1, int(p * n))]

            return {
                "steps": self.steps,
                "window": n,
                "stalls": self.stalls,
                "last_stall": dict(self.last_stall) if self.last_stall else None,
                "step_s": {
                    "p50": pct(0.50),
                    "p95": pct(0.95),
                    "max": durations[-1] if n else 0.0,
                },
                "fill": {
                    "mean": (
                        sum(self._fills) / len(self._fills)
                        if self._fills
                        else 0.0
                    ),
                },
            }


class StallProfiler:
    """Turns a stall event into a bounded XProf artifact.

    Wired as ``FlightRecorder.on_stall``: on a stall it spawns a daemon
    thread that runs ``observability.profiling.trace`` for
    ``duration_s`` seconds, capturing the steps *after* the stall (the
    stall itself already happened; what matters is whether the engine is
    still degraded). Bounded three ways: at most one capture in flight,
    at most one per ``cooldown_s``, each ``duration_s`` long. Skipped
    stalls are counted, never queued.

    Lives here rather than profiling.py so the import chain stays
    jax-free (the gateway imports server imports flight); jax is only
    touched inside the capture thread, and only when a stall actually
    fires with profiling enabled. ``trace_fn`` is injectable for tests.
    """

    def __init__(self, log_dir, *, cooldown_s: float = 300.0,
                 duration_s: float = 2.0,
                 clock: Optional[Callable[[], float]] = None,
                 trace_fn: Optional[Callable] = None):
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        if duration_s <= 0:
            raise ValueError(f"duration_s must be > 0, got {duration_s}")
        self.log_dir = pathlib.Path(log_dir)
        self.cooldown_s = float(cooldown_s)
        self.duration_s = float(duration_s)
        self.clock = clock or time.monotonic
        self._trace_fn = trace_fn
        self._lock = threading.Lock()
        self._active = False
        self._last_start: Optional[float] = None
        self._seq = 0
        self.skipped = 0
        self.captures: list = []
        self.last_error: Optional[str] = None

    def on_stall(self, info: Optional[dict]) -> bool:
        """FlightRecorder callback; returns True when a capture starts.
        Never raises — the engine drive loop is above this call."""
        with self._lock:
            now = self.clock()
            in_cooldown = (
                self._last_start is not None
                and now - self._last_start < self.cooldown_s
            )
            if self._active or in_cooldown:
                self.skipped += 1
                return False
            self._active = True
            self._last_start = now
            self._seq += 1
            seq = self._seq
        threading.Thread(
            target=self._capture,
            args=(dict(info or {}), seq),
            name=f"stall-profile-{seq}",
            daemon=True,
        ).start()
        return True

    def _capture(self, info: dict, seq: int) -> None:
        try:
            trace_fn = self._trace_fn
            if trace_fn is None:
                from kubeflow_tpu.observability.profiling import trace
                trace_fn = trace
            with trace_fn(self.log_dir, f"stall-{seq:03d}") as path:
                time.sleep(self.duration_s)
            with self._lock:
                self.captures.append({
                    "seq": seq,
                    "path": str(path),
                    "stall": info,
                })
        except Exception as exc:  # profiling must never hurt serving
            with self._lock:
                self.last_error = f"{type(exc).__name__}: {exc}"
        finally:
            with self._lock:
                self._active = False

    def summary(self) -> dict:
        """Surfaced under /stats next to the flight recorder's ledger."""
        with self._lock:
            return {
                "captures": len(self.captures),
                "skipped": self.skipped,
                "last": dict(self.captures[-1]) if self.captures else None,
                "last_error": self.last_error,
                "cooldown_s": self.cooldown_s,
            }


def stall_profiler_from_env(
    clock: Optional[Callable[[], float]] = None,
) -> Optional[StallProfiler]:
    """None unless KUBEFLOW_TPU_STALL_PROFILE_DIR is set (capture stays
    off by default). Raises on garbage knob values."""
    from kubeflow_tpu.webhook.tpu_env import (
        KUBEFLOW_TPU_STALL_PROFILE_COOLDOWN_S,
        KUBEFLOW_TPU_STALL_PROFILE_DIR,
        KUBEFLOW_TPU_STALL_PROFILE_SECONDS,
    )

    log_dir = os.environ.get(KUBEFLOW_TPU_STALL_PROFILE_DIR, "").strip()
    if not log_dir:
        return None

    def _positive(name, default, minimum):
        value = os.environ.get(name, "").strip()
        if not value:
            return default
        try:
            got = float(value)
        except ValueError:
            got = minimum - 1
        if got < minimum:
            raise ValueError(f"{name}={value!r}: want a number >= {minimum}")
        return got

    return StallProfiler(
        log_dir,
        cooldown_s=_positive(
            KUBEFLOW_TPU_STALL_PROFILE_COOLDOWN_S, 300.0, 0
        ),
        duration_s=_positive(KUBEFLOW_TPU_STALL_PROFILE_SECONDS, 2.0, 0.001),
        clock=clock,
    )
