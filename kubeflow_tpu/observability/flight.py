"""Engine flight recorder: rolling step-time window with stall detection.

The per-step spans answer "where did THIS request's time go"; the flight
recorder answers "what has the engine been doing for the last N steps" —
cheap enough to stay on unconditionally (a deque append per step), so it is
populated even when tracing is sampled out or disabled. The serving layer
surfaces ``snapshot()`` under ``/stats`` and mirrors the stall count into
the ``tpu_engine_step_stall_total`` Prometheus counter.

Stall rule: a step is a stall when its duration exceeds ``stall_factor`` ×
the rolling median of the current window, once ``min_samples`` steps have
been observed (the guard keeps the first JAX compilations — orders of
magnitude slower than steady-state steps — from flagging every warm step
after them, and from being flagged against an empty window).
"""

from __future__ import annotations

import statistics
import threading
import time
from collections import deque
from typing import Callable, Optional


class FlightRecorder:
    """Thread-compatible: the engine drive loop records; HTTP handler
    threads snapshot. A lock keeps the window and counters coherent."""

    def __init__(
        self,
        window: int = 256,
        stall_factor: float = 8.0,
        min_samples: int = 16,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.window = max(2, int(window))
        self.stall_factor = float(stall_factor)
        self.min_samples = max(2, int(min_samples))
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._durations: deque = deque(maxlen=self.window)
        self._fills: deque = deque(maxlen=self.window)
        self.steps = 0
        self.stalls = 0
        self.last_stall: Optional[dict] = None

    def record_step(
        self, duration_s: float, fill: Optional[float] = None
    ) -> bool:
        """Record one engine step; returns True when the step is a stall
        (caller attaches the span event / bumps the counter)."""
        with self._lock:
            stalled = False
            if len(self._durations) >= self.min_samples:
                median = statistics.median(self._durations)
                if median > 0 and duration_s > self.stall_factor * median:
                    stalled = True
                    self.stalls += 1
                    self.last_stall = {
                        "at": self.clock(),
                        "step": self.steps,
                        "duration_s": duration_s,
                        "median_s": median,
                        "factor": duration_s / median,
                    }
            self._durations.append(duration_s)
            if fill is not None:
                self._fills.append(fill)
            self.steps += 1
            return stalled

    def snapshot(self) -> dict:
        """Point-in-time view for ``/stats``: recent step-time distribution,
        fill, and the stall ledger."""
        with self._lock:
            durations = sorted(self._durations)
            n = len(durations)

            def pct(p: float) -> float:
                if not n:
                    return 0.0
                return durations[min(n - 1, int(p * n))]

            return {
                "steps": self.steps,
                "window": n,
                "stalls": self.stalls,
                "last_stall": dict(self.last_stall) if self.last_stall else None,
                "step_s": {
                    "p50": pct(0.50),
                    "p95": pct(0.95),
                    "max": durations[-1] if n else 0.0,
                },
                "fill": {
                    "mean": (
                        sum(self._fills) / len(self._fills)
                        if self._fills
                        else 0.0
                    ),
                },
            }
