"""Observability: tracing spans (metrics live in kubeflow_tpu.metrics)."""

from kubeflow_tpu.observability.tracing import (  # noqa: F401
    InMemoryExporter,
    Span,
    Tracer,
    TracerProvider,
    get_tracer,
    set_tracer_provider,
)
