"""Observability: tracing spans + engine flight recorder (metrics live in
kubeflow_tpu.metrics)."""

from kubeflow_tpu.observability.flight import FlightRecorder  # noqa: F401
from kubeflow_tpu.observability.tracing import (  # noqa: F401
    InMemoryExporter,
    JSONLExporter,
    RingBufferExporter,
    Span,
    Tracer,
    TracerProvider,
    configure_from_env,
    current_span,
    format_traceparent,
    get_tracer,
    parse_traceparent,
    set_tracer_provider,
    trace_ring,
)
