"""In-notebook TPU profiling helpers.

Thin policy wrapper over jax.profiler for the notebook workflow: capture
a trace around N training steps, write it where the notebook's PVC (or
/tmp) can serve it to TensorBoard/XProf, and annotate steps so the trace
viewer shows model steps instead of anonymous XLA modules.

    from kubeflow_tpu.observability.profiling import trace
    with trace("/home/jovyan/profiles", "train"):
        for _ in range(3):
            state, loss = step(state, tokens)
    # → tensorboard --logdir /home/jovyan/profiles

The reference's only tracing is OTel on the admission webhook
(SURVEY.md §5 — "No continuous profiling"); device-side profiling is a
TPU-native addition for the in-notebook half of the framework.
"""

from __future__ import annotations

import contextlib
import pathlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(
    log_dir: str | pathlib.Path,
    name: str = "trace",
) -> Iterator[pathlib.Path]:
    """Capture a device+host profiler trace for the enclosed block."""
    path = pathlib.Path(log_dir) / name
    path.mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(str(path), create_perfetto_link=False)
    try:
        yield path
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def step_annotation(name: str, step: Optional[int] = None) -> Iterator[None]:
    """Label the enclosed work in the trace viewer (StepTraceAnnotation)."""
    if step is not None:
        ctx = jax.profiler.StepTraceAnnotation(name, step_num=step)
    else:
        ctx = jax.profiler.TraceAnnotation(name)
    with ctx:
        yield


def timed_steps(step_fn, state, batches, sync_every: int = 1):
    """Drive ``state, loss = step_fn(state, batch)`` and return
    (state, per-step wall seconds). Forces a device sync every
    ``sync_every`` steps so the timings measure device work, not
    dispatch — the first entry includes compile time by design (report
    it separately or discard it)."""
    times = []
    loss = None
    for i, batch in enumerate(batches):
        t0 = time.perf_counter()
        with step_annotation("train_step", step=i):
            state, loss = step_fn(state, batch)
        if (i + 1) % sync_every == 0:
            jax.block_until_ready(loss)
        times.append(time.perf_counter() - t0)
    if loss is not None and times:
        # Trailing steps since the last sync are still in flight; charge
        # their device time to the final entry so sum(times) reflects all
        # device work, as documented.
        t0 = time.perf_counter()
        jax.block_until_ready(loss)
        times[-1] += time.perf_counter() - t0
    return state, times
