#!/usr/bin/env python
"""Pin release image tags into the generated manifests.

Reference analogue: releasing/update-manifests-images — the reference
edits kustomize image overrides; here the config/ tree is generated, so
this edits the single source of truth (the generator defaults in
kubeflow_tpu/deploy/manifests.py) and re-renders.

Usage: python releasing/update_manifests_images.py v0.2.0
"""

from __future__ import annotations

import pathlib
import re
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
GENERATOR = REPO / "kubeflow_tpu" / "deploy" / "manifests.py"
MANAGED_IMAGES = (
    "kubeflow-tpu/notebook-controller",
    "kubeflow-tpu/platform-notebook-controller",
)


def main() -> int:
    if len(sys.argv) != 2 or not re.fullmatch(r"v\d+\.\d+\.\d+", sys.argv[1]):
        print(__doc__)
        return 2
    tag = sys.argv[1]
    src = GENERATOR.read_text()
    for image in MANAGED_IMAGES:
        pattern = re.escape(image) + r":[A-Za-z0-9._-]+"
        if not re.search(pattern, src):
            print(f"ERROR: {image} not found in {GENERATOR}")
            return 1
        src = re.sub(pattern, f"{image}:{tag}", src)
    GENERATOR.write_text(src)
    subprocess.run([sys.executable, str(REPO / "ci" / "generate_manifests.py")], check=True)
    version_file = REPO / "releasing" / "version" / "VERSION"
    version_file.write_text(tag.lstrip("v") + "\n")
    print(f"pinned {', '.join(MANAGED_IMAGES)} to {tag} and re-rendered config/")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
