#!/usr/bin/env python
"""Fleet serving load test: prefix-affinity vs random routing.

Drives the SAME multi-tenant workload (every tenant opens with its own
shared system prompt — several full KV blocks — followed by a unique
per-request tail) through two fresh fleets of real
``InferenceServer`` replicas over ``PagedBatcher(prefix_cache=True)``
tiny models, fronted by ``ServingGateway``:

- ``affinity``: consistent-hash routing on the prompt's longest shared
  prefix chain key — every tenant's traffic lands on the replica whose
  block pool already holds its system prompt, so admissions skip the
  shared blocks' prefill;
- ``random``: uniform spread — each replica keeps re-prefilling (and,
  under block-pool pressure, re-evicting) every tenant's prefix.

Each replica's block pool is sized to hold only ~tenants/replicas warm
chains beyond its active slots: the fleet CAN cache every tenant's
prefix collectively, but no single replica can cache all of them — the
capacity argument for affinity routing.

Per-request TTFT is the wall-clock to the first SSE token through the
gateway; throughput is completed requests over the measured wall time.
Both arms get warm-up rounds at identical shapes so compile time never
lands in the measured numbers. Prefix hit/miss/eviction counts are the
engines' own counters (the same numbers the gateway scrapes from
``/stats`` and Prometheus exports as
``tpu_serving_prefix_cache_*_total``), measured as deltas across the
timed phase.

A separate churn phase then proves elasticity on a live fleet: a third
replica joins mid-run and a drained replica leaves mid-run, with zero
failed (non-re-routed) requests end to end.

Each measured arm also runs the fleet telemetry plane
(``observability/signals.py``) and queries it over HTTP: the run gates
on ``/debug/signals`` TTFT p95 agreeing with the clients' own stopwatch
(±15%, small absolute floor) and on ``/debug/slo`` reporting ZERO
breaches for a healthy fleet — the SLO gate. Both summaries are stamped
into the artifact.

The artifact (default SERVE_r07_fleet.json, written atomically) records
both arms; the win condition is affinity throughput ≥ 1.2× random at a
p95 TTFT no worse than random's, with zero churn failures.

``--smoke`` shrinks to 2 replicas × 2 tenants × 2 rounds on the tiny
model, skips the artifact and the win gate (executability only) — the
integration-workflow tier.

Sibling experiments share the harness: ``--disagg`` (prefill/decode
tier split, SERVE_r08_disagg.json), ``--evict-storm`` (HBM economy:
bf16 evict+re-prefill vs int8 KV + host-RAM swap on one byte budget,
SERVE_r09_hbm.json), ``--ring-churn`` (fleet KV tier: join/leave churn,
peer prefix fetch vs re-prefill vs a static ring,
SERVE_r12_peerkv.json), and ``--spec`` / ``--multilora`` (speculative
decoding as a ragged scheduling mode, token-exact vs plain; 64-adapter
multi-LoRA fleet with (prefix, adapter) affinity vs adapter-oblivious
routing — both into SERVE_r10_spec.json).

Usage: python loadtest/serve_fleet.py [--out SERVE_r07_fleet.json]
       [--replicas 3] [--tenants 6] [--rounds 6] [--smoke]
       [--disagg | --evict-storm | --spec --multilora]
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BLOCK_SIZE = 16
# Shared system prompt length in full KV blocks. Long enough that the
# prompt's prefill dominates per-request compute — the work a prefix-
# cache hit skips. --smoke shrinks it (module global, set once in main).
PREFIX_BLOCKS = 16
TAIL_TOKENS = 15           # unique per-request suffix
DECODE_TOKENS = 4


def _p95_ms(values) -> float:
    """Nearest-rank p95 in milliseconds — ONE formula for every artifact
    field, so the affinity and random numbers can never drift."""
    return round(sorted(values)[max(0, int(0.95 * len(values)) - 1)] * 1e3, 2)


def _tenant_prompt(tenant: int, nonce: int, vocab: int) -> list:
    """System prompt shared by ALL of a tenant's requests + a unique
    tail. Deterministic (no RNG): token ids are arithmetic in a band per
    tenant, far from special ids."""
    prefix_len = PREFIX_BLOCKS * BLOCK_SIZE
    prefix = [3 + (tenant * 131 + i * 7) % (vocab - 4)
              for i in range(prefix_len)]
    tail = [3 + (nonce * 17 + i * 11) % (vocab - 4)
            for i in range(TAIL_TOKENS)]
    return prefix + tail


_MODEL = None


def _load_model():
    """One tiny model for every replica in the process (weights are
    identical across the fleet in production too)."""
    global _MODEL
    if _MODEL is None:
        import jax

        from kubeflow_tpu.models import llama as L

        cfg = L.LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        jax.block_until_ready(params)
        _MODEL = (params, cfg)
    return _MODEL


def _record_host() -> str:
    """``tpu`` or ``cpu`` next to ``provenance`` in every artifact: a
    smoke record from a CPU runner must never read like a chip number."""
    import jax

    return "tpu" if jax.default_backend() in ("tpu", "axon") else "cpu"


SLOTS = 2


def _pool_blocks(warm_chain_blocks: int) -> int:
    """ONE pool size for every engine in the run: jit shapes include the
    pool dims, so the shape warm-up only pays off if warm engine,
    measured replicas, and churn replicas all agree."""
    prompt_len = PREFIX_BLOCKS * BLOCK_SIZE + TAIL_TOKENS
    per_seq = -(-(prompt_len + DECODE_TOKENS) // BLOCK_SIZE) + 1
    return SLOTS * per_seq + warm_chain_blocks + 2


def _make_engine(warm_chain_blocks: int):
    from kubeflow_tpu.models.paged import PagedBatcher
    from kubeflow_tpu.models.serving import GenerationConfig

    params, cfg = _load_model()
    return PagedBatcher(
        params, cfg,
        gen=GenerationConfig(max_new_tokens=DECODE_TOKENS, eos_id=-1),
        slots=SLOTS, num_blocks=_pool_blocks(warm_chain_blocks),
        block_size=BLOCK_SIZE,
        prompt_bucket=PREFIX_BLOCKS * BLOCK_SIZE + 2 * BLOCK_SIZE,
        prefix_cache=True,
    )


def _warm_shapes(warm_chain_blocks: int) -> None:
    """Compile every prefill shape either arm can encounter BEFORE any
    arm is timed. The jit cache is process-wide, so whichever arm runs
    first would otherwise pay the compiles for both: a cache hit at m
    matched blocks prefills only the remaining suffix, and each m is a
    distinct padded shape. Partial evictions make every m in
    [0, PREFIX_BLOCKS] reachable. Dims match the replicas exactly —
    a compile at other pool dims warms nothing."""
    _, cfg = _load_model()
    pb = _make_engine(warm_chain_blocks)
    base = _tenant_prompt(0, 0, cfg.vocab_size)
    pb.submit(base, max_new_tokens=DECODE_TOKENS)  # m=0: full prefill
    pb.run()
    for m in range(1, PREFIX_BLOCKS + 1):
        shared = base[:m * BLOCK_SIZE]
        rest = [5 + m] * (len(base) - len(shared))
        pb.submit(shared + rest, max_new_tokens=DECODE_TOKENS)
        pb.run()


def _build_replicas(n: int, warm_chain_blocks: int):
    """n fresh InferenceServers over prefix-cached tiny PagedBatchers.
    Block pool: active slots' worst case + the configured warm-chain
    budget (+2 spare so back-to-back admissions do not immediately evict
    a warm chain) — sized so the fleet collectively caches every
    tenant's prefix but no single replica can cache all of them."""
    from kubeflow_tpu.models.server import InferenceServer

    _, cfg = _load_model()
    servers = []
    for _ in range(n):
        servers.append(InferenceServer(
            _make_engine(warm_chain_blocks), port=0, drain_s=2.0,
        ).start())
    return servers, cfg


def _stream_once(gw, prompt, tenant: str, timeout: float = 120.0,
                 max_tokens: int = 0):
    """One streaming completion through the gateway. Returns
    (ok, ttft_seconds, detail)."""
    conn = http.client.HTTPConnection(gw.host, gw.port, timeout=timeout)
    try:
        t0 = time.perf_counter()
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": prompt, "stream": True,
                        "max_tokens": max_tokens or DECODE_TOKENS,
                        "user": tenant}).encode(),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        if resp.status != 200:
            return False, 0.0, f"HTTP {resp.status}"
        ttft = None
        finished = False
        error = None
        while True:
            line = resp.fp.readline()
            if not line:
                break
            if not line.startswith(b"data:"):
                continue
            if line == b"data: [DONE]\n":
                finished = True
                break
            if ttft is None:
                ttft = time.perf_counter() - t0
            if b'"error"' in line:
                error = line.decode().strip()
        if not finished or error:
            return False, ttft or 0.0, error or "truncated stream"
        return True, ttft, ""
    except OSError as err:
        return False, 0.0, str(err)
    finally:
        conn.close()


def _drive_round(gw, tenants: int, nonce_base: int, vocab: int,
                 outcomes: list) -> None:
    """One round: every tenant issues one streaming request,
    concurrently (its own thread) — the gateway sees the interleaved
    multi-tenant arrival pattern routing decisions matter for."""
    threads = []
    for t in range(tenants):
        prompt = _tenant_prompt(t, nonce_base + t, vocab)

        def work(p=prompt, name=f"tenant-{t}"):
            outcomes.append(_stream_once(gw, p, name))

        th = threading.Thread(target=work, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join()


def _prefix_totals(servers) -> dict:
    hits = sum(s.engine.prefix_hits for s in servers)
    misses = sum(s.engine.prefix_misses for s in servers)
    evictions = sum(s.engine.prefix_evictions for s in servers)
    return {"hits": hits, "misses": misses, "evictions": evictions}


def _debug_json(gw, path: str) -> dict:
    """GET a gateway /debug endpoint — over HTTP on purpose, so the run
    exercises the JSON surface an operator (or the autoscaler) uses, not
    the in-process objects."""
    conn = http.client.HTTPConnection(gw.host, gw.port, timeout=10)
    try:
        conn.request("GET", path)
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def _build_telemetry():
    """Telemetry plane for one measured arm. Objectives are generous
    (the SLO gate asserts a HEALTHY run is silent, not that a tiny CPU
    model is fast); the window ring still spans the 30m slow window."""
    from kubeflow_tpu.observability.signals import (
        FleetTelemetry,
        SignalsConfig,
    )
    from kubeflow_tpu.observability.slo import default_objectives

    return FleetTelemetry(
        SignalsConfig(window_s=5.0, windows=360),
        objectives=default_objectives(
            ttft_p95_s=5.0, inter_token_p95_s=2.0, queue_wait_p95_s=5.0,
        ),
    )


def run_arm(affinity: str, *, replicas: int, tenants: int, rounds: int,
            warm_chain_blocks: int, warmup_rounds: int = 2) -> dict:
    from kubeflow_tpu.models.gateway import ServingGateway

    servers, cfg = _build_replicas(replicas, warm_chain_blocks)
    telemetry = _build_telemetry()
    gw = ServingGateway(
        [f"{s.host}:{s.port}" for s in servers], port=0,
        affinity=affinity, block_size=BLOCK_SIZE,
        health_interval_s=0.2, reroute_budget=2,
    ).start()
    try:
        # Warm-up: identical shapes (full-prefill AND cached-suffix
        # admissions both compile here), excluded from timing.
        for r in range(warmup_rounds):
            sink: list = []
            _drive_round(gw, tenants, 1_000_000 + r * tenants,
                         cfg.vocab_size, sink)
            bad = [d for ok, _, d in sink if not ok]
            if bad:
                raise RuntimeError(f"warm-up failures: {bad}")
        # Attach the telemetry plane only now: its series must cover
        # exactly the measured rounds, or cold warm-up TTFTs would skew
        # the p95 the agreement gate compares against the clients'.
        gw.telemetry = telemetry
        gw._tenant_buckets = telemetry.tenants
        before = _prefix_totals(servers)
        outcomes: list = []
        t0 = time.perf_counter()
        for r in range(rounds):
            _drive_round(gw, tenants, r * tenants, cfg.vocab_size,
                         outcomes)
        wall = time.perf_counter() - t0
        after = _prefix_totals(servers)
        gw.probe_once()  # final scrape → gateway-side aggregate view
        stats = gw.stats()
        signals = _debug_json(gw, "/debug/signals")
        slo = _debug_json(gw, "/debug/slo")
        failures = [d for ok, _, d in outcomes if not ok]
        ttfts = [ttft for ok, ttft, _ in outcomes if ok]
        completed = len(ttfts)
        hits = after["hits"] - before["hits"]
        misses = after["misses"] - before["misses"]
        # Telemetry-plane agreement: the gateway-measured TTFT p95 (the
        # autoscaler's input) vs the clients' own stopwatch, 15% with a
        # small absolute floor for loopback-scale jitter on tiny TTFTs.
        client_p95_ms = _p95_ms(ttfts) if ttfts else None
        tel_p95_s = (signals.get("fleet", {}).get("ttft_s") or {}).get("p95")
        tel_p95_ms = round(tel_p95_s * 1e3, 2) if tel_p95_s else None
        agrees = (
            client_p95_ms is not None and tel_p95_ms is not None
            and abs(tel_p95_ms - client_p95_ms)
            <= max(0.15 * client_p95_ms, 25.0)
        )
        breaches = sum(
            o["breaches_total"] for o in slo.get("objectives", {}).values()
        )
        return {
            "routing": affinity,
            "requests_completed": completed,
            "failures": failures,
            "requests_per_sec": round(completed / wall, 2),
            "p95_ttft_ms": _p95_ms(ttfts),
            "mean_ttft_ms": round(sum(ttfts) / len(ttfts) * 1e3, 2),
            "wall_s": round(wall, 3),
            "prefix_cache": {
                "hits": hits,
                "misses": misses,
                "evictions": after["evictions"] - before["evictions"],
                "hit_ratio": round(hits / (hits + misses), 4)
                if hits + misses else 0.0,
            },
            "gateway": {
                "reroutes": stats["reroutes"],
                "shed": stats["shed"],
                "failed": stats["failed"],
                "fleet_prefix_cache": stats.get("fleet_prefix_cache"),
            },
            # Telemetry plane vs client ground truth + the SLO verdict
            # (satellite: stamped into SERVE_*.json; smoke gates on it).
            "signals": {
                "ttft_p95_ms": tel_p95_ms,
                "client_p95_ttft_ms": client_p95_ms,
                "agrees_within_15pct": agrees,
                "requests_per_s": signals.get("fleet", {}).get(
                    "requests_per_s"),
            },
            "slo": {
                "breaching": slo.get("breaching", []),
                "breaches_total": breaches,
            },
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()


def run_churn(*, tenants: int, rounds: int,
              warm_chain_blocks: int) -> dict:
    """Elasticity on a live fleet: traffic flows while a replica JOINS
    (added to the ring mid-run) and another DRAINS (stop() flips its
    healthz; the probe routes around it while in-flight work finishes).
    Every request must complete — re-routed is fine, failed is not."""
    from kubeflow_tpu.models.gateway import ServingGateway

    servers, cfg = _build_replicas(2, warm_chain_blocks)
    gw = ServingGateway(
        [f"{s.host}:{s.port}" for s in servers], port=0,
        affinity="prefix", block_size=BLOCK_SIZE,
        health_interval_s=0.1, reroute_budget=2,
    ).start()
    joiner = None
    try:
        sink: list = []
        _drive_round(gw, tenants, 2_000_000, cfg.vocab_size, sink)  # warm
        outcomes: list = []
        events = []
        for r in range(rounds):
            if r == rounds // 3:
                (joiner,), _ = _build_replicas(1, warm_chain_blocks)
                gw.add_replica(f"{joiner.host}:{joiner.port}")
                events.append(f"round {r}: replica joined")
            if r == 2 * rounds // 3:
                threading.Thread(target=servers[0].stop,
                                 daemon=True).start()
                events.append(f"round {r}: replica draining")
            _drive_round(gw, tenants, 3_000_000 + r * tenants,
                         cfg.vocab_size, outcomes)
        deadline = time.monotonic() + 30
        want = {f"{s.host}:{s.port}" for s in (servers[1], joiner)}
        while gw.ring_nodes() != frozenset(want) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        stats = gw.stats()
        failures = [d for ok, _, d in outcomes if not ok]
        return {
            "requests": len(outcomes),
            "failures": failures,
            "events": events,
            "reroutes": stats["reroutes"],
            "gateway_failed": stats["failed"],
            "ring_converged": gw.ring_nodes() == frozenset(want),
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()
        if joiner is not None:
            joiner.stop()


def _verify_trace_export(min_chains: int):
    """When ``KUBEFLOW_TPU_TRACE_EXPORT`` is set, the run doubles as the
    tracing executability gate: the JSONL export must contain a complete
    gateway→engine span chain (gateway.request → gateway.route →
    server.request → queue_wait → prefill, one shared trace id) for at
    least every completed request in the measured arms. Returns a small
    summary dict, or None when export is off."""
    from kubeflow_tpu.webhook.tpu_env import KUBEFLOW_TPU_TRACE_EXPORT

    path = os.environ.get(KUBEFLOW_TPU_TRACE_EXPORT, "")
    if not path:
        return None
    chain = {"gateway.request", "gateway.route", "server.request",
             "queue_wait", "prefill"}
    by_trace: dict = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            span = json.loads(line)
            by_trace.setdefault(span["trace_id"], set()).add(span["name"])
    chains = sum(1 for names in by_trace.values() if chain <= names)
    if chains < min_chains:
        raise SystemExit(
            f"trace export {path}: only {chains} complete gateway→engine "
            f"span chains for {min_chains} completed requests"
        )
    print(f"# trace export: {chains} complete gateway→engine chains "
          f"across {len(by_trace)} traces ({path})", file=sys.stderr)
    return {"complete_chains": chains, "traces": len(by_trace)}


# -- disaggregated prefill/decode arm (--disagg) ------------------------

DISAGG_LONG_BLOCKS = 12    # storm prompt length, in full KV blocks
DISAGG_SHORT_TOKENS = 20   # one full block + a short tail
DISAGG_DECODE_TOKENS = 10  # 9 inter-token gaps per short request
DISAGG_SLOTS = 4


def _disagg_prompt(nonce: int, length: int, vocab: int) -> list:
    """Unique prompt per request (arithmetic in the nonce, no RNG): the
    storm measures PREFILL interference with decode, so nothing may
    prefix-hit and skip its prefill."""
    return [3 + (nonce * 131 + i * 7) % (vocab - 4) for i in range(length)]


def _make_disagg_engine():
    from kubeflow_tpu.models.paged import PagedBatcher, pool_blocks_from_hbm
    from kubeflow_tpu.models.serving import GenerationConfig

    params, cfg = _load_model()
    bucket = (DISAGG_LONG_BLOCKS + 2) * BLOCK_SIZE
    per_seq = -(-(bucket + DISAGG_DECODE_TOKENS) // BLOCK_SIZE) + 1
    floor = DISAGG_SLOTS * per_seq + 2
    # Pools size themselves from the device's real HBM budget
    # (memory_stats) on TPU; on CPU (no memory_stats) the fallback IS
    # the computed worst-case constant, and the max() keeps a tiny HBM
    # answer from under-sizing below what the slots can demand.
    blocks = max(pool_blocks_from_hbm(
        cfg, BLOCK_SIZE, fraction=0.3, fallback=floor), floor)
    return PagedBatcher(
        params, cfg,
        gen=GenerationConfig(max_new_tokens=DISAGG_DECODE_TOKENS,
                             eos_id=-1),
        slots=DISAGG_SLOTS, num_blocks=blocks, block_size=BLOCK_SIZE,
        prompt_bucket=bucket, prefix_cache=True,
    )


def _build_disagg_fleet(mode: str):
    """mode="disagg": 1 prefill + 2 decode replicas behind a tier-aware
    gateway; mode="fused": the control — 3 fused replicas, same engines
    and total capacity, only the tier split differs."""
    from kubeflow_tpu.models.gateway import ServingGateway
    from kubeflow_tpu.models.server import InferenceServer

    _, cfg = _load_model()
    roles = (["prefill", "decode", "decode"] if mode == "disagg"
             else ["fused"] * 3)
    servers = [
        InferenceServer(_make_disagg_engine(), port=0, drain_s=2.0,
                        tier_role=role).start()
        for role in roles
    ]
    tier_roles = {f"{s.host}:{s.port}": role
                  for s, role in zip(servers, roles) if role != "fused"}
    gw = ServingGateway(
        [f"{s.host}:{s.port}" for s in servers], port=0,
        affinity="prefix", block_size=BLOCK_SIZE, health_interval_s=0.2,
        reroute_budget=2,
        tier_mode="disagg" if mode == "disagg" else "fused",
        tier_roles=tier_roles,
    ).start()
    return gw, servers, cfg


def _stream_gaps(gw, prompt, tenant: str, timeout: float = 120.0):
    """One streaming completion; returns (ok, [inter-token gaps in
    seconds], detail). The gaps — wall-clock between consecutive SSE
    data lines at the client — are the decode-interference signal the
    disagg arm gates on."""
    conn = http.client.HTTPConnection(gw.host, gw.port, timeout=timeout)
    try:
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": prompt, "stream": True,
                        "max_tokens": DISAGG_DECODE_TOKENS,
                        "user": tenant}).encode(),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        if resp.status != 200:
            return False, [], f"HTTP {resp.status}"
        gaps: list = []
        last = None
        finished = False
        error = None
        while True:
            line = resp.fp.readline()
            if not line:
                break
            if not line.startswith(b"data:"):
                continue
            if line == b"data: [DONE]\n":
                finished = True
                break
            if b'"error"' in line:
                error = line.decode().strip()
                continue
            now = time.perf_counter()
            if last is not None:
                gaps.append(now - last)
            last = now
        if not finished or error:
            return False, gaps, error or "truncated stream"
        return True, gaps, ""
    except OSError as err:
        return False, [], str(err)
    finally:
        conn.close()


def _drive_disagg_round(gw, vocab: int, nonce_base: int, per_round: int,
                        long_every: int, outcomes: list) -> None:
    """One concurrent round. long_every=0 → all-short (the quiet
    baseline); long_every=4 → the 1-in-4 long-prompt storm."""
    threads = []
    for i in range(per_round):
        is_long = bool(long_every) and i % long_every == 0
        length = (DISAGG_LONG_BLOCKS * BLOCK_SIZE + 3 if is_long
                  else DISAGG_SHORT_TOKENS)
        prompt = _disagg_prompt(nonce_base + i, length, vocab)

        def work(p=prompt, lng=is_long, name=f"tenant-{i % 4}"):
            ok, gaps, detail = _stream_gaps(gw, p, name)
            outcomes.append((lng, ok, gaps, detail))

        th = threading.Thread(target=work, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join()


def run_disagg_arm(mode: str, *, rounds: int, per_round: int) -> dict:
    gw, servers, cfg = _build_disagg_fleet(mode)
    telemetry = _build_telemetry()
    try:
        # Warm-up: one storm-shaped round compiles EVERY shape either
        # phase can hit (short/long prefill, KV export gathers, import
        # writes at both block counts) before anything is timed.
        sink: list = []
        _drive_disagg_round(gw, cfg.vocab_size, 5_000_000, per_round, 4,
                            sink)
        bad = [d for _, ok, _, d in sink if not ok]
        if bad:
            raise RuntimeError(f"{mode} warm-up failures: {bad}")
        gw.telemetry = telemetry
        gw._tenant_buckets = telemetry.tenants
        quiet: list = []
        for r in range(rounds):
            _drive_disagg_round(gw, cfg.vocab_size, r * per_round,
                                per_round, 0, quiet)
        storm: list = []
        for r in range(rounds):
            _drive_disagg_round(gw, cfg.vocab_size,
                                1_000_000 + r * per_round, per_round, 4,
                                storm)
        gw.probe_once()
        stats = gw.stats()
        signals = _debug_json(gw, "/debug/signals")
        slo = _debug_json(gw, "/debug/slo")
        failures = [d for _, ok, _, d in quiet + storm if not ok]
        quiet_gaps = [g for _, ok, gaps, _ in quiet if ok for g in gaps]
        # The gate reads SHORT requests only: a long request's own gaps
        # say nothing about cross-request interference.
        storm_gaps = [g for lng, ok, gaps, _ in storm
                      if ok and not lng for g in gaps]
        quiet_p95 = _p95_ms(quiet_gaps) if quiet_gaps else 0.0
        storm_p95 = _p95_ms(storm_gaps) if storm_gaps else 0.0
        breaches = sum(o["breaches_total"]
                       for o in slo.get("objectives", {}).values())
        return {
            "mode": mode,
            "requests_completed": sum(
                1 for _, ok, _, _ in quiet + storm if ok),
            "failures": failures,
            "quiet_inter_token_p95_ms": quiet_p95,
            "storm_inter_token_p95_ms": storm_p95,
            "storm_over_quiet": round(storm_p95 / max(quiet_p95, 1e-9), 3),
            "kv_transfers": stats["kv_transfers"],
            "kv_transfer_failures": stats["kv_transfer_failures"],
            "kv_transfer_bytes": stats["kv_transfer_bytes"],
            "kv_transfer_latency_s": stats["kv_transfer_latency_s"],
            "signals_kv_transfer_s": (signals.get("fleet") or {}).get(
                "kv_transfer_s"),
            "slo": {
                "breaching": slo.get("breaching", []),
                "breaches_total": breaches,
            },
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()


def main_disagg(args) -> int:
    """--disagg: the tier-split experiment. The disagg fleet's decode
    tier must stay flat through the long-prompt storm (p95 inter-token
    ≤ 1.1× its own quiet baseline, small absolute floor for loopback
    jitter) while the same-capacity fused fleet degrades — plus the PR
    11 SLO gate (zero breaches) and zero failed requests on both arms."""
    global DISAGG_LONG_BLOCKS, DISAGG_DECODE_TOKENS
    rounds, per_round = 3, 8
    if args.smoke:
        DISAGG_LONG_BLOCKS, DISAGG_DECODE_TOKENS = 4, 6
        rounds, per_round = 1, 4
    print("# disagg arm: 1 prefill + 2 decode replicas, 1-in-4 "
          "long-prompt storm ...", file=sys.stderr)
    disagg = run_disagg_arm("disagg", rounds=rounds, per_round=per_round)
    print("# fused control arm (same engines, no tier split) ...",
          file=sys.stderr)
    fused = run_disagg_arm("fused", rounds=rounds, per_round=per_round)

    record = {
        "scenario": (
            f"1-in-4 long-prompt storm ({DISAGG_LONG_BLOCKS} blocks) over "
            "a 1-prefill + 2-decode tier split with paged-KV handoff vs "
            "the same 3 engines fused"
        ),
        "model": "tiny",
        "block_size": BLOCK_SIZE,
        "long_blocks": DISAGG_LONG_BLOCKS,
        "decode_tokens": DISAGG_DECODE_TOKENS,
        "rounds": rounds,
        "per_round": per_round,
        "provenance": "smoke" if args.smoke else "live",
        "host": _record_host(),
        "mesh": {"tp": 1},  # single-chip replicas
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "disagg": disagg,
        "fused": fused,
    }
    print(json.dumps({
        "disagg_quiet_p95_ms": disagg["quiet_inter_token_p95_ms"],
        "disagg_storm_p95_ms": disagg["storm_inter_token_p95_ms"],
        "disagg_storm_over_quiet": disagg["storm_over_quiet"],
        "fused_storm_over_quiet": fused["storm_over_quiet"],
        "kv_transfers": disagg["kv_transfers"],
        "kv_transfer_failures": disagg["kv_transfer_failures"],
        "slo_breaches": (disagg["slo"]["breaches_total"]
                         + fused["slo"]["breaches_total"]),
    }))
    clean = (
        not disagg["failures"] and not fused["failures"]
        and disagg["kv_transfers"] > 0
        and disagg["kv_transfer_failures"] == 0
        and disagg["slo"]["breaches_total"] == 0
        and fused["slo"]["breaches_total"] == 0
    )
    if not clean:
        print("# disagg gate FAILED: " + json.dumps({
            "disagg_failures": disagg["failures"],
            "fused_failures": fused["failures"],
            "kv": {k: disagg[k] for k in
                   ("kv_transfers", "kv_transfer_failures")},
            "slo": {"disagg": disagg["slo"], "fused": fused["slo"]},
        }), file=sys.stderr)
    if args.smoke:
        print("# --smoke: artifact write and win gate skipped",
              file=sys.stderr)
        return 0 if clean else 1
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(f"# wrote {args.out}", file=sys.stderr)
    flat = (
        disagg["storm_inter_token_p95_ms"]
        <= max(1.1 * disagg["quiet_inter_token_p95_ms"],
               disagg["quiet_inter_token_p95_ms"] + 10.0)
    )
    degrades = fused["storm_over_quiet"] > 1.1
    win = clean and flat and degrades
    if not win:
        print("# win gate: " + json.dumps({
            "decode_tier_flat": flat, "fused_degrades": degrades,
        }), file=sys.stderr)
    return 0 if win else 1


# -- HBM-economy eviction-storm arm (--evict-storm) ---------------------

EVICT_PREFIX_BLOCKS = 6    # each tenant's chain, in full KV blocks
EVICT_TAIL_TOKENS = 7      # unique per-request suffix
EVICT_DECODE_TOKENS = 8
EVICT_SLOTS = 2
EVICT_BUDGET_CHAINS = 4    # warm chains the bf16 baseline pool can hold


def _evict_prompt(tenant: int, nonce: int, vocab: int) -> list:
    """Per-TENANT chain (shared across the tenant's returns) + a unique
    tail, deterministic like _tenant_prompt but sized by the evict-storm
    globals."""
    prefix = [3 + (tenant * 131 + i * 7) % (vocab - 4)
              for i in range(EVICT_PREFIX_BLOCKS * BLOCK_SIZE)]
    tail = [3 + (nonce * 17 + i * 11) % (vocab - 4)
            for i in range(EVICT_TAIL_TOKENS)]
    return prefix + tail


def _evict_block_bytes(kv_bits: int) -> int:
    """Measured (not derived) per-block HBM bytes for the pool format:
    sum the probe pool's leaf bytes so the bf16 and int8 arms are sized
    from the SAME byte budget the engine actually allocates."""
    from kubeflow_tpu.models.paged import PagedBatcher

    params, cfg = _load_model()
    probe = PagedBatcher(params, cfg, slots=1, num_blocks=2,
                         block_size=BLOCK_SIZE, prompt_bucket=BLOCK_SIZE,
                         kv_bits=kv_bits)
    return sum(leaf.nbytes for leaf in probe.pool.values()) // 2


def _make_evict_engine(kv_bits: int, num_blocks: int, swap_bytes: int):
    from kubeflow_tpu.models.paged import PagedBatcher
    from kubeflow_tpu.models.serving import GenerationConfig

    params, cfg = _load_model()
    prompt_len = EVICT_PREFIX_BLOCKS * BLOCK_SIZE + EVICT_TAIL_TOKENS
    return PagedBatcher(
        params, cfg,
        gen=GenerationConfig(max_new_tokens=EVICT_DECODE_TOKENS, eos_id=-1),
        slots=EVICT_SLOTS, num_blocks=num_blocks, block_size=BLOCK_SIZE,
        prompt_bucket=-(-prompt_len // BLOCK_SIZE) * BLOCK_SIZE,
        prefix_cache=True, kv_bits=kv_bits, swap_bytes=swap_bytes,
        # Block-wide admission pieces: ONE prefill shape regardless of
        # how many chain blocks hit, so TTFT tracks blocks actually
        # prefilled instead of which padded bucket they landed in.
        admit_chunk=BLOCK_SIZE,
    )


def _evict_pool_floor() -> int:
    prompt_len = EVICT_PREFIX_BLOCKS * BLOCK_SIZE + EVICT_TAIL_TOKENS
    per_seq = -(-(prompt_len + EVICT_DECODE_TOKENS) // BLOCK_SIZE) + 1
    return EVICT_SLOTS * per_seq + 2


def _stream_evict(host, port, prompt, tenant: str, timeout: float = 120.0):
    """One streaming completion straight at a replica (no gateway: the
    storm is a single-chip HBM story). Returns (ok, ttft_s, [inter-token
    gaps s], detail) — TTFT carries the re-prefill vs swap-restore
    signal, the gaps isolate decode speed from admission work."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        t0 = time.perf_counter()
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": prompt, "stream": True,
                        "max_tokens": EVICT_DECODE_TOKENS,
                        "user": tenant}).encode(),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        if resp.status != 200:
            return False, 0.0, [], f"HTTP {resp.status}"
        ttft = None
        gaps: list = []
        last = None
        finished = False
        error = None
        while True:
            line = resp.fp.readline()
            if not line:
                break
            if not line.startswith(b"data:"):
                continue
            if line == b"data: [DONE]\n":
                finished = True
                break
            if b'"error"' in line:
                error = line.decode().strip()
                continue
            now = time.perf_counter()
            if ttft is None:
                ttft = now - t0
            if last is not None:
                gaps.append(now - last)
            last = now
        if not finished or error:
            return False, ttft or 0.0, gaps, error or "truncated stream"
        return True, ttft, gaps, ""
    except OSError as err:
        return False, 0.0, [], str(err)
    finally:
        conn.close()


def _drive_evict_round(server, tenants: int, nonce_base: int, vocab: int,
                       outcomes: list) -> None:
    """Every tenant returns once, concurrently — with a pool that holds
    only EVICT_BUDGET_CHAINS warm chains, each admission evicts someone
    else's chain: the storm."""
    threads = []
    for t in range(tenants):
        prompt = _evict_prompt(t, nonce_base + t, vocab)

        def work(p=prompt, name=f"tenant-{t}"):
            outcomes.append(_stream_evict(server.host, server.port, p,
                                          name))

        th = threading.Thread(target=work, daemon=True)
        th.start()
        threads.append(th)
        # The whole tenant set connecting in the same instant overflows
        # the single replica's accept backlog (ECONNRESET) before the
        # storm even starts; the spread is negligible vs round duration.
        time.sleep(0.01)
    for th in threads:
        th.join()


def run_evict_arm(label: str, kv_bits: int, swap: bool, *, tenants: int,
                  rounds: int, hbm_bytes: int) -> dict:
    """One arm of the storm on one replica sized from ``hbm_bytes``:
    the baseline (bf16, no swap) loses every demoted chain to a full
    re-prefill; the treatment (int8 + host swap) fits ~2x the chains on
    chip and restores the rest from host RAM."""
    from kubeflow_tpu.models.gateway import prompt_chain_keys
    from kubeflow_tpu.models.server import InferenceServer

    _, cfg = _load_model()
    per_block = _evict_block_bytes(kv_bits)
    num_blocks = max(_evict_pool_floor(), hbm_bytes // per_block)
    chain_bytes = EVICT_PREFIX_BLOCKS * per_block
    swap_bytes = 2 * tenants * chain_bytes if swap else 0
    engine = _make_evict_engine(kv_bits, num_blocks, swap_bytes)
    server = InferenceServer(engine, port=0, drain_s=2.0).start()
    try:
        sink: list = []
        _drive_evict_round(server, tenants, 4_000_000, cfg.vocab_size,
                           sink)  # warm-up: compiles + first prefills
        bad = [d for ok, _, _, d in sink if not ok]
        if bad:
            raise RuntimeError(f"{label} warm-up failures: {bad}")
        before_hits = engine.prefix_hits
        before_misses = engine.prefix_misses
        outcomes: list = []
        t0 = time.perf_counter()
        for r in range(rounds):
            _drive_evict_round(server, tenants, r * tenants,
                               cfg.vocab_size, outcomes)
        wall = time.perf_counter() - t0
        failures = [d for ok, _, _, d in outcomes if not ok]
        ttfts = [ttft for ok, ttft, _, _ in outcomes if ok]
        gaps = [g for ok, _, gs, _ in outcomes if ok for g in gs]
        # Concurrent resident sessions: tenants whose FULL chain is
        # device-resident after the storm — the pool-capacity number the
        # int8 halving is supposed to double.
        with server._lock:
            resident = 0
            for t in range(tenants):
                keys = prompt_chain_keys(
                    _evict_prompt(t, 0, cfg.vocab_size)
                    [:EVICT_PREFIX_BLOCKS * BLOCK_SIZE], BLOCK_SIZE)
                if all(k in engine._prefix_entries for k in keys):
                    resident += 1
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=10)
        try:
            conn.request("GET", "/stats")
            stats = json.loads(conn.getresponse().read())
        finally:
            conn.close()
        hits = engine.prefix_hits - before_hits
        misses = engine.prefix_misses - before_misses
        return {
            "arm": label,
            "kv_bits": kv_bits,
            "swap_enabled": swap,
            "num_blocks": num_blocks,
            "pool_bytes": num_blocks * per_block,
            "requests_completed": len(ttfts),
            "failures": failures,
            "resident_sessions": resident,
            "p95_ttft_ms": _p95_ms(ttfts) if ttfts else None,
            "mean_ttft_ms": round(sum(ttfts) / len(ttfts) * 1e3, 2)
            if ttfts else None,
            # Inter-token gaps isolate decode speed from admission work;
            # the 5% gate compares the arms on THIS number.
            "decode_tokens_per_sec": round(len(gaps) / sum(gaps), 2)
            if gaps else None,
            "wall_s": round(wall, 3),
            "prefix_cache": {
                "hits": hits,
                "misses": misses,
                "hit_ratio": round(hits / (hits + misses), 4)
                if hits + misses else 0.0,
            },
            "kv_swap": stats.get("kv_swap"),
            "kv_pool": stats.get("kv_pool"),
        }
    finally:
        server.stop()


def main_evict(args) -> int:
    """--evict-storm: oversubscribed tenants cycling through one
    replica's pool. Baseline bf16/no-swap re-prefills every returning
    chain; the int8+swap treatment must hold >= 2x the resident sessions
    on the same byte budget, decode within 5%, and beat the baseline's
    p95 TTFT via swap restores."""
    global EVICT_PREFIX_BLOCKS, EVICT_DECODE_TOKENS, EVICT_BUDGET_CHAINS
    tenants, rounds = args.tenants * 2, args.rounds
    if args.smoke:
        # Small model/short chains, but still OVERSUBSCRIBED — for BOTH
        # arms: 12 tenants x 3 blocks must exceed even the int8 pool
        # (~2x the baseline's blocks), or the treatment never demotes
        # and the swap path goes unexercised.
        EVICT_PREFIX_BLOCKS, EVICT_DECODE_TOKENS = 3, 4
        EVICT_BUDGET_CHAINS = 1
        tenants, rounds = 12, 2
    # ONE byte budget for both arms: what the bf16 pool needs to keep
    # EVICT_BUDGET_CHAINS chains warm beyond its active slots. The int8
    # arm spends the same bytes on ~2x the blocks.
    hbm_bytes = _evict_block_bytes(0) * (
        _evict_pool_floor() + EVICT_BUDGET_CHAINS * EVICT_PREFIX_BLOCKS
    )
    if not args.smoke:
        # The storm must oversubscribe BOTH pools (the smoke shrink
        # states the same principle): if every tenant chain fits the
        # ~2x block count the int8 arm buys with this budget, the
        # treatment never demotes and the swap path goes unexercised —
        # so size the tenant set off the int8 pool, not the bf16 one.
        int8_blocks = hbm_bytes // _evict_block_bytes(8)
        tenants = max(tenants, int8_blocks // EVICT_PREFIX_BLOCKS + 2)
    print(f"# evict-storm baseline: bf16, no swap ({tenants} tenants x "
          f"{rounds} rounds, {hbm_bytes} pool bytes) ...", file=sys.stderr)
    baseline = run_evict_arm("evict_reprefill", 0, False, tenants=tenants,
                             rounds=rounds, hbm_bytes=hbm_bytes)
    print("# evict-storm treatment: int8 KV + host-RAM swap ...",
          file=sys.stderr)
    treatment = run_evict_arm("int8_swap", 8, True, tenants=tenants,
                              rounds=rounds, hbm_bytes=hbm_bytes)

    resident_ratio = round(
        treatment["resident_sessions"]
        / max(baseline["resident_sessions"], 1), 3)
    decode_ratio = round(
        (treatment["decode_tokens_per_sec"] or 0.0)
        / max(baseline["decode_tokens_per_sec"] or 1e-9, 1e-9), 3)
    record = {
        "scenario": (
            f"{tenants} tenants with {EVICT_PREFIX_BLOCKS}-block chains "
            "cycling through one replica whose pool holds "
            f"{EVICT_BUDGET_CHAINS} warm bf16 chains: evict+re-prefill "
            "vs int8 KV + host-RAM swap on the same byte budget"
        ),
        "model": "tiny",
        "block_size": BLOCK_SIZE,
        "prefix_blocks": EVICT_PREFIX_BLOCKS,
        "decode_tokens": EVICT_DECODE_TOKENS,
        "tenants": tenants,
        "rounds": rounds,
        "pool_byte_budget": hbm_bytes,
        "provenance": "smoke" if args.smoke else "live",
        "host": _record_host(),
        "mesh": {"tp": 1},  # single-chip replicas
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "baseline": baseline,
        "treatment": treatment,
        "resident_sessions_ratio": resident_ratio,
        "decode_tokens_per_sec_ratio": decode_ratio,
    }
    print(json.dumps({
        "baseline_resident_sessions": baseline["resident_sessions"],
        "treatment_resident_sessions": treatment["resident_sessions"],
        "resident_sessions_ratio": resident_ratio,
        "baseline_p95_ttft_ms": baseline["p95_ttft_ms"],
        "treatment_p95_ttft_ms": treatment["p95_ttft_ms"],
        "decode_tokens_per_sec_ratio": decode_ratio,
        "swap_out": (treatment["kv_swap"] or {}).get("swap_out"),
        "swap_in": (treatment["kv_swap"] or {}).get("swap_in"),
    }))
    swap_stats = treatment["kv_swap"] or {}
    clean = (
        not baseline["failures"] and not treatment["failures"]
        and swap_stats.get("swap_out", 0) > 0
        and swap_stats.get("swap_in", 0) > 0
    )
    if not clean:
        print("# evict-storm gate FAILED: " + json.dumps({
            "baseline_failures": baseline["failures"],
            "treatment_failures": treatment["failures"],
            "kv_swap": swap_stats,
        }), file=sys.stderr)
    if args.smoke:
        print("# --smoke: artifact write and win gate skipped",
              file=sys.stderr)
        return 0 if clean else 1
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(f"# wrote {args.out}", file=sys.stderr)
    win = (
        clean
        and resident_ratio >= 2.0
        and decode_ratio >= 0.95
        and treatment["p95_ttft_ms"] < baseline["p95_ttft_ms"]
    )
    if not win:
        print("# win gate: " + json.dumps({
            "resident_ratio_ge_2x": resident_ratio >= 2.0,
            "decode_within_5pct": decode_ratio >= 0.95,
            "swap_beats_reprefill_ttft":
                treatment["p95_ttft_ms"] < baseline["p95_ttft_ms"],
        }), file=sys.stderr)
    return 0 if win else 1


# ---------------------------------------------------------------------------
# --spec / --multilora (r10): speculation as a ragged scheduling mode +
# multi-LoRA serving with (prefix, adapter) affinity routing.
# ---------------------------------------------------------------------------

SPEC_SLOTS = 2             # decode slots; each contributes 1+k verify rows
SPEC_K = 7                 # draft length (verify span = 8 rows/slot)
SPEC_REQUESTS = 6
SPEC_DECODE_TOKENS = 32
SPEC_DAMP = 0.05           # per-layer residual damping (see _spec_models)

ML_REPLICAS = 4
ML_ADAPTERS = 64
ML_CACHE_SLOTS = 16        # hot adapters resident per replica
ML_LOAD_S = 0.02           # simulated adapter-load stall on a cache miss
ML_ROUNDS = 3
ML_PREFIX_TOKENS = 16      # ONE system prompt shared by every adapter
ML_TAIL_TOKENS = 5
ML_DECODE_TOKENS = 6
ML_CONCURRENCY = 16


def _spec_models():
    """Target in a draft-friendly regime: damp the per-layer residual
    contributions so the embed/head pair (SHARED with the truncated
    draft) dominates the argmax. A 1-layer draft then agrees with the
    full target often — the high-acceptance regime a trained draft
    earns — while every miss still exercises the real verify-reject-
    rollback machinery, and the token-exactness gate is checked against
    the plain scheduler either way."""
    import jax.tree_util as jtu

    from kubeflow_tpu.models.speculative import truncated_draft

    params, cfg = _load_model()
    params = dict(params, layers=jtu.tree_map(
        lambda x: x * SPEC_DAMP, params["layers"]))
    dparams, dcfg = truncated_draft(params, cfg, 1)
    return params, cfg, dparams, dcfg


def _bench_decode(engine, prompts):
    """Warm-up pass (compiles every dispatch shape), then one timed
    pass of the same prompts: (sorted streams, tokens/sec, wall_s)."""
    for p in prompts:
        engine.submit(p)
    engine.run()
    t0 = time.perf_counter()
    for p in prompts:
        engine.submit(p)
    out = engine.run()
    wall = time.perf_counter() - t0
    toks = sum(len(v) for v in out.values())
    return (sorted(tuple(v) for v in out.values()),
            round(toks / wall, 2), round(wall, 3))


def run_spec_arm() -> dict:
    """Engine-level decode bench: plain ragged PagedBatcher vs the SAME
    engine in speculative scheduling mode (each slot contributing
    1+k_spec verify rows to the fused dispatch). The streams must be
    token-identical; the speedup is rounds saved by acceptance."""
    from kubeflow_tpu.models.paged import PagedBatcher
    from kubeflow_tpu.models.serving import GenerationConfig
    from kubeflow_tpu.models.speculative import SpeculativePagedBatcher

    params, cfg, dparams, dcfg = _spec_models()
    gen = GenerationConfig(max_new_tokens=SPEC_DECODE_TOKENS, eos_id=-1)
    prompts = [[3 + (s * 37 + i) % (cfg.vocab_size - 4) for i in range(6)]
               for s in range(SPEC_REQUESTS)]
    kw = dict(gen=gen, slots=SPEC_SLOTS, num_blocks=64, block_size=8,
              prompt_bucket=16)
    plain = PagedBatcher(params, cfg, attn_kernel=False, ragged=True,
                         token_budget=4 * SPEC_SLOTS, **kw)
    plain_out, plain_tps, plain_wall = _bench_decode(plain, prompts)
    spec = SpeculativePagedBatcher(
        params, cfg, dparams, dcfg, k_spec=SPEC_K, ragged=True,
        token_budget=SPEC_SLOTS * (SPEC_K + 1), **kw)
    spec_out, spec_tps, spec_wall = _bench_decode(spec, prompts)
    return {
        "requests": SPEC_REQUESTS,
        "slots": SPEC_SLOTS,
        "k_spec": SPEC_K,
        "decode_tokens": SPEC_DECODE_TOKENS,
        "token_exact": plain_out == spec_out,
        "plain_tokens_per_sec": plain_tps,
        "spec_tokens_per_sec": spec_tps,
        "speedup": round(spec_tps / max(plain_tps, 1e-9), 3),
        "acceptance_rate": round(spec.acceptance_rate, 4),
        "verify_rounds": spec.rounds,
        "plain_wall_s": plain_wall,
        "spec_wall_s": spec_wall,
    }


def _ml_prompt(adapter_id: int, nonce: int, vocab: int) -> list:
    """ONE system prompt shared across every adapter (the worst case
    for an adapter-oblivious prefix router: all 64 adapters' traffic
    hashes to a single replica) + a unique per-request tail."""
    prefix = [3 + (i * 7) % (vocab - 4) for i in range(ML_PREFIX_TOKENS)]
    tail = [3 + (adapter_id * 131 + nonce * 17 + i * 11) % (vocab - 4)
            for i in range(ML_TAIL_TOKENS)]
    return prefix + tail


def _ml_build_fleet(adapter_affinity: bool):
    from kubeflow_tpu.models.gateway import ServingGateway
    from kubeflow_tpu.models.lora import LoraConfig, init_lora_params
    from kubeflow_tpu.models.multilora import (
        MultiLoraPagedBatcher,
        stack_adapters,
    )
    from kubeflow_tpu.models.server import InferenceServer
    from kubeflow_tpu.models.serving import GenerationConfig

    import jax

    params, cfg = _load_model()
    lcfg = LoraConfig(rank=2, targets=("wq", "wv"))
    adapters = [init_lora_params(cfg, lcfg, jax.random.PRNGKey(seed))
                for seed in range(ML_ADAPTERS)]
    stacked = stack_adapters(adapters, cfg, lcfg)
    names = [f"ad{i}" for i in range(ML_ADAPTERS)]
    servers = []
    for _ in range(ML_REPLICAS):
        engine = MultiLoraPagedBatcher(
            params, cfg, stacked, lcfg, adapter_names=names,
            gen=GenerationConfig(max_new_tokens=ML_DECODE_TOKENS,
                                 eos_id=-1),
            slots=4, num_blocks=64, block_size=8, prompt_bucket=32,
            attn_kernel=False, ragged=True, token_budget=16,
            lora_cache_slots=ML_CACHE_SLOTS, lora_load_s=ML_LOAD_S,
        )
        servers.append(InferenceServer(
            engine, port=0, drain_s=2.0,
            max_queue_depth=4 * ML_ADAPTERS,  # queue, don't shed: the
            # oblivious arm funnels the whole fleet's load to one
            # replica and the p95 must show that, not 429s
        ).start())
    gw = ServingGateway(
        [f"{s.host}:{s.port}" for s in servers], port=0, block_size=8,
        health_interval_s=0.2, upstream_timeout_s=600.0,
        adapter_affinity=adapter_affinity,
    ).start()
    return gw, servers, cfg


def _ml_stream(gw, prompt, model, timeout: float = 600.0,
               max_tokens: int = None):
    """One streaming completion with an adapter selection. Returns
    (ok, ttft_seconds, detail)."""
    body = {"prompt": prompt, "stream": True,
            "max_tokens": max_tokens or ML_DECODE_TOKENS}
    if model is not None:
        body["model"] = model
    conn = http.client.HTTPConnection(gw.host, gw.port, timeout=timeout)
    try:
        t0 = time.perf_counter()
        conn.request("POST", "/v1/completions", json.dumps(body).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            return False, 0.0, f"HTTP {resp.status}"
        ttft = None
        finished = False
        error = None
        while True:
            line = resp.fp.readline()
            if not line:
                break
            if not line.startswith(b"data:"):
                continue
            if line == b"data: [DONE]\n":
                finished = True
                break
            if ttft is None:
                ttft = time.perf_counter() - t0
            if b'"error"' in line:
                error = line.decode().strip()
        if not finished or error:
            return False, ttft or 0.0, error or "truncated stream"
        return True, ttft, ""
    except OSError as err:
        return False, 0.0, str(err)
    finally:
        conn.close()


def run_multilora_arm(label: str, adapter_affinity: bool) -> dict:
    """One routing arm over a fresh fleet: ML_ADAPTERS adapters sharing
    ONE system prompt over ML_REPLICAS replicas whose hot-adapter cache
    holds ML_CACHE_SLOTS. (prefix, adapter) affinity spreads the
    adapters so each replica's share fits its cache; the oblivious
    router sends everything to the prefix's one ring owner, which then
    thrashes adapter loads forever (and serves the fleet's whole load
    alone)."""
    gw, servers, cfg = _ml_build_fleet(adapter_affinity)
    try:
        # Warm-up straight at each replica (no gateway, base model):
        # both arms compile the same shapes regardless of routing.
        for s in servers:
            class _GW:  # _ml_stream wants .host/.port
                host, port = s.host, s.port
            ok, _, detail = _ml_stream(_GW, _ml_prompt(0, 10**6,
                                                       cfg.vocab_size),
                                       None)
            if not ok:
                raise RuntimeError(f"{label} warm-up failure: {detail}")
        outcomes: list = []
        sem = threading.Semaphore(ML_CONCURRENCY)
        t0 = time.perf_counter()
        for rnd in range(ML_ROUNDS):
            threads = []
            for a in range(ML_ADAPTERS):
                prompt = _ml_prompt(a, rnd, cfg.vocab_size)

                def work(p=prompt, m=f"ad{a}"):
                    with sem:
                        got = _ml_stream(gw, p, m)
                        if not got[0] and "Errno" in got[2]:
                            # Transient loopback reset under the
                            # accept burst: one client-side retry,
                            # like any production client.
                            got = _ml_stream(gw, p, m)
                        outcomes.append(got)

                th = threading.Thread(target=work, daemon=True)
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
        wall = time.perf_counter() - t0
        failures = [d for ok, _, d in outcomes if not ok]
        ttfts = [t for ok, t, _ in outcomes if ok]
        cache = {"hits": 0, "misses": 0, "evictions": 0}
        served_by = []  # adapter-cache touches per replica: the spread
        for s in servers:
            st = s.engine.lora_cache_stats()
            for k in cache:
                cache[k] += st[k]
            served_by.append(st["hits"] + st["misses"])
        total = cache["hits"] + cache["misses"]
        return {
            "arm": label,
            "adapter_affinity": adapter_affinity,
            "replicas": ML_REPLICAS,
            "adapters": ML_ADAPTERS,
            "cache_slots": ML_CACHE_SLOTS,
            "rounds": ML_ROUNDS,
            "requests_completed": len(ttfts),
            "failures": failures,
            "p95_ttft_ms": _p95_ms(ttfts) if ttfts else None,
            "mean_ttft_ms": round(sum(ttfts) / len(ttfts) * 1e3, 2)
            if ttfts else None,
            "requests_per_sec": round(len(ttfts) / wall, 2),
            "wall_s": round(wall, 3),
            "lora_cache": {
                **cache,
                "hit_ratio": round(cache["hits"] / total, 4)
                if total else 0.0,
            },
            # How many replicas actually took traffic: the spread the
            # adapter salt buys (oblivious: 1).
            "replicas_serving": sum(1 for n in served_by if n > 0),
            "served_by_replica": served_by,
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()


def main_spec(args) -> int:
    """--spec / --multilora: speculation + multi-LoRA serving record
    (artifact: SERVE_r10_spec.json, sections for whichever arms ran)."""
    global SPEC_K, SPEC_REQUESTS, SPEC_DECODE_TOKENS
    global ML_REPLICAS, ML_ADAPTERS, ML_CACHE_SLOTS, ML_LOAD_S
    global ML_ROUNDS, ML_CONCURRENCY
    if args.smoke:
        SPEC_K, SPEC_REQUESTS, SPEC_DECODE_TOKENS = 4, 2, 8
        ML_REPLICAS, ML_ADAPTERS, ML_CACHE_SLOTS = 2, 8, 4
        ML_LOAD_S, ML_ROUNDS, ML_CONCURRENCY = 0.01, 2, 8
    record: dict = {
        "model": "tiny",
        "provenance": "smoke" if args.smoke else "live",
        "host": _record_host(),
        "mesh": {"tp": 1},  # single-chip replicas
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    summary: dict = {}
    ok = True
    if args.spec:
        print(f"# spec arm: {SPEC_REQUESTS} requests x "
              f"{SPEC_DECODE_TOKENS} tokens, k_spec={SPEC_K} ...",
              file=sys.stderr)
        spec = run_spec_arm()
        record["speculative"] = spec
        summary.update({
            "spec_token_exact": spec["token_exact"],
            "spec_speedup": spec["speedup"],
            "spec_acceptance_rate": spec["acceptance_rate"],
        })
        ok = ok and spec["token_exact"]
        if not args.smoke:
            ok = ok and spec["speedup"] >= 1.5
    if args.multilora:
        print(f"# multilora affinity arm: {ML_ADAPTERS} adapters over "
              f"{ML_REPLICAS} replicas x {ML_ROUNDS} rounds ...",
              file=sys.stderr)
        affinity = run_multilora_arm("adapter_affinity", True)
        print("# multilora oblivious arm (fresh fleet) ...",
              file=sys.stderr)
        oblivious = run_multilora_arm("adapter_oblivious", False)
        record["multilora"] = {"affinity": affinity,
                               "oblivious": oblivious}
        summary.update({
            "ml_affinity_p95_ttft_ms": affinity["p95_ttft_ms"],
            "ml_oblivious_p95_ttft_ms": oblivious["p95_ttft_ms"],
            "ml_affinity_hit_ratio":
                affinity["lora_cache"]["hit_ratio"],
            "ml_oblivious_hit_ratio":
                oblivious["lora_cache"]["hit_ratio"],
            "ml_replicas_serving": affinity["replicas_serving"],
        })
        ok = ok and not affinity["failures"] and not oblivious["failures"]
        if not args.smoke:
            ok = (ok
                  and affinity["p95_ttft_ms"] < oblivious["p95_ttft_ms"]
                  and affinity["replicas_serving"] > 1)
    print(json.dumps(summary))
    if args.smoke:
        print("# --smoke: artifact write and win gate skipped",
              file=sys.stderr)
        return 0 if ok else 1
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(f"# wrote {args.out}", file=sys.stderr)
    if not ok:
        print("# r10 win gate FAILED", file=sys.stderr)
    return 0 if ok else 1


# ---------------------------------------------------------------------------
# --tp (r13): one tensor-parallel mesh replica vs a fleet of 1-chip ones.
#
# A serving "replica" is a MESH, not a chip (models/tp_serving.py): the
# tp=TP_DEGREE arm runs ONE ragged PagedBatcher whose weights shard over
# the tp axis and whose block pool head-shards — one HTTP endpoint over
# TP_DEGREE chips — against a fleet of TP_DEGREE single-chip replicas on
# the same chip budget. Token streams must match the single-chip engine
# exactly; the structural win is per-chip pool bytes dropping by the TP
# degree (the headroom a big model's weights need).
# ---------------------------------------------------------------------------

TP_DEGREE = 4
TP_SLOTS = 2
TP_REQUESTS = 12
TP_DECODE_TOKENS = 24
TP_CONCURRENCY = 4
TP_NUM_BLOCKS = 64


def _tp_build_engine(plan):
    from kubeflow_tpu.models.paged import PagedBatcher
    from kubeflow_tpu.models.serving import GenerationConfig

    params, cfg = _load_model()
    return PagedBatcher(
        params, cfg,
        gen=GenerationConfig(max_new_tokens=TP_DECODE_TOKENS, eos_id=-1),
        slots=TP_SLOTS, num_blocks=TP_NUM_BLOCKS, block_size=8,
        prompt_bucket=16, attn_kernel=False, ragged=True,
        token_budget=4 * TP_SLOTS, plan=plan,
    )


def _tp_pool_bytes_per_chip(engine) -> int:
    """Pool bytes resident on ONE chip: the engine's pool shards homed
    on its first device (a 1-chip engine has exactly one shard per
    leaf, so this is the whole pool)."""
    total, dev = 0, None
    for leaf in engine.pool.values():
        shards = leaf.addressable_shards
        if dev is None:
            dev = shards[0].device
        total += sum(s.data.nbytes for s in shards if s.device == dev)
    return total


def _tp_greedy_consistent(prompts, streams) -> bool:
    """tp's psum order can fork a bf16 near-tie (the --spec arm's known
    caveat): a diverged stream still passes if every token sits on the
    greedy path of its own prompt within ~1.5 bf16 ulps (0.05 at these
    logit magnitudes — a wrong token misses by whole logits)."""
    import jax.numpy as jnp

    from kubeflow_tpu.models import llama as L

    params, cfg = _load_model()
    for prompt, toks in zip(prompts, streams):
        full = jnp.asarray([list(prompt) + list(toks)])
        logits = L.forward(params, cfg, full)[0]
        for i, tok in enumerate(toks):
            row = logits[len(prompt) - 1 + i]
            if float(row.max() - row[tok]) > 0.05:
                return False
    return True


def run_tp_arm(tp: int) -> dict:
    """One fleet arm: tp>1 → ONE mesh replica spanning tp chips behind
    the gateway; tp==1 → TP_DEGREE single-chip replicas. Same gateway
    plumbing, same workload, same chip budget."""
    from kubeflow_tpu.models.gateway import ServingGateway
    from kubeflow_tpu.models.server import InferenceServer
    from kubeflow_tpu.models.tp_serving import serving_plan

    _, cfg = _load_model()
    n_replicas = 1 if tp > 1 else TP_DEGREE
    engines = [
        _tp_build_engine(serving_plan(tp, cfg=cfg) if tp > 1 else None)
        for _ in range(n_replicas)
    ]
    servers = [
        InferenceServer(e, port=0, drain_s=2.0,
                        max_queue_depth=4 * TP_REQUESTS).start()
        for e in engines
    ]
    gw = ServingGateway(
        [f"{s.host}:{s.port}" for s in servers], port=0, block_size=8,
        health_interval_s=0.2, upstream_timeout_s=600.0,
    ).start()
    try:
        prompts = [
            [3 + (r * 29 + i * 13) % (cfg.vocab_size - 4)
             for i in range(6 + r % 5)]
            for r in range(TP_REQUESTS)
        ]
        # Warm-up straight at each replica: both arms compile their
        # dispatch shapes outside the timed window.
        for s in servers:
            class _GW:  # _ml_stream wants .host/.port
                host, port = s.host, s.port
            ok, _, detail = _ml_stream(_GW, prompts[0], None,
                                       max_tokens=TP_DECODE_TOKENS)
            if not ok:
                raise RuntimeError(f"tp arm warm-up failure: {detail}")
        outcomes: list = []
        sem = threading.Semaphore(TP_CONCURRENCY)
        threads = []
        t0 = time.perf_counter()
        for prompt in prompts:

            def work(p=prompt):
                with sem:
                    got = _ml_stream(gw, p, None,
                                     max_tokens=TP_DECODE_TOKENS)
                    if not got[0] and "Errno" in got[2]:
                        # Transient loopback reset under the accept
                        # burst: one client-side retry.
                        got = _ml_stream(gw, p, None,
                                         max_tokens=TP_DECODE_TOKENS)
                    outcomes.append(got)

            th = threading.Thread(target=work, daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        wall = time.perf_counter() - t0
        failures = [d for ok, _, d in outcomes if not ok]
        ttfts = [t for ok, t, _ in outcomes if ok]
        return {
            "arm": f"tp{tp}_mesh_replica" if tp > 1 else "single_chip_fleet",
            "replicas": n_replicas,
            "chips": n_replicas * max(1, tp),
            "mesh": getattr(engines[0], "mesh_axes", None) or {"tp": 1},
            "requests_completed": len(ttfts),
            "failures": failures,
            "p95_ttft_ms": _p95_ms(ttfts) if ttfts else None,
            "decode_tokens_per_sec":
                round(len(ttfts) * TP_DECODE_TOKENS / wall, 2),
            "wall_s": round(wall, 3),
            "pool_bytes_per_chip": _tp_pool_bytes_per_chip(engines[0]),
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()


def main_tp(args) -> int:
    """--tp: one tensor-parallel mesh replica vs a same-chip-budget
    fleet of single-chip replicas (artifact: SERVE_r13_tp.json)."""
    global TP_REQUESTS, TP_DECODE_TOKENS, TP_CONCURRENCY
    if os.environ.get("JAX_PLATFORMS", "cpu").startswith("cpu"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            # CPU runners: enough virtual devices for the mesh. Only
            # effective before the first backend touch — which is why
            # this runs before anything imports a model.
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{TP_DEGREE}").strip()
    import jax

    if jax.device_count() < TP_DEGREE:
        print(f"# --tp needs {TP_DEGREE} devices, have "
              f"{jax.device_count()} (set XLA_FLAGS="
              "--xla_force_host_platform_device_count)", file=sys.stderr)
        return 1
    if args.smoke:
        TP_REQUESTS, TP_DECODE_TOKENS, TP_CONCURRENCY = 4, 6, 2

    from kubeflow_tpu.models.tp_serving import serving_plan

    record: dict = {
        "model": "tiny",
        "provenance": "smoke" if args.smoke else "live",
        "host": _record_host(),
        "mesh": {"tp": TP_DEGREE},
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "tp_degree": TP_DEGREE,
    }
    # Engine-level token parity first: the mesh replica must emit the
    # SAME streams as a single-chip engine before any fleet numbers
    # mean anything (near-tie forks fall back to greedy-consistency).
    parity_prompts = [[5, 9, 17], [3, 41, 90, 7], [11] * 9]

    def _streams(plan):
        eng = _tp_build_engine(plan)
        rids = [eng.submit(p) for p in parity_prompts]
        out = eng.run()
        return [list(out[r]) for r in rids]

    want = _streams(None)
    got = _streams(serving_plan(TP_DEGREE, cfg=_load_model()[1]))
    token_exact = want == got
    greedy_ok = token_exact or _tp_greedy_consistent(parity_prompts, got)
    record["token_exact"] = token_exact
    record["greedy_consistent"] = greedy_ok

    print(f"# tp arm: ONE tp={TP_DEGREE} mesh replica, "
          f"{TP_REQUESTS} requests ...", file=sys.stderr)
    mesh_arm = run_tp_arm(TP_DEGREE)
    print(f"# 1-chip fleet arm: {TP_DEGREE} replicas (fresh fleet) ...",
          file=sys.stderr)
    fleet_arm = run_tp_arm(1)
    record["mesh_replica"] = mesh_arm
    record["single_chip_fleet"] = fleet_arm
    ratio = (fleet_arm["pool_bytes_per_chip"]
             / max(1, mesh_arm["pool_bytes_per_chip"]))
    record["pool_bytes_per_chip_ratio"] = round(ratio, 3)
    print(json.dumps({
        "tp_token_exact": token_exact,
        "tp_greedy_consistent": greedy_ok,
        "tp_p95_ttft_ms": mesh_arm["p95_ttft_ms"],
        "fleet_p95_ttft_ms": fleet_arm["p95_ttft_ms"],
        "tp_decode_tokens_per_sec": mesh_arm["decode_tokens_per_sec"],
        "fleet_decode_tokens_per_sec":
            fleet_arm["decode_tokens_per_sec"],
        "pool_bytes_per_chip_ratio": record["pool_bytes_per_chip_ratio"],
    }))
    # The gate is structural, not a CPU horse race: token parity (exact,
    # or greedy-consistent when a bf16 near-tie forks under tp's psum
    # order), zero failures, and the head-sharded pool's per-chip bytes
    # down by ~the TP degree. Tokens/sec is recorded, judged on chips.
    ok = (greedy_ok
          and not mesh_arm["failures"] and not fleet_arm["failures"]
          and ratio >= TP_DEGREE * 0.9)
    if args.smoke:
        print("# --smoke: artifact write and win gate skipped",
              file=sys.stderr)
        return 0 if ok else 1
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(f"# wrote {args.out}", file=sys.stderr)
    if not ok:
        print("# r13 gate FAILED: " + json.dumps({
            "token_exact": token_exact,
            "greedy_consistent": greedy_ok,
            "pool_ratio_ge": ratio >= TP_DEGREE * 0.9,
            "mesh_failures": mesh_arm["failures"],
            "fleet_failures": fleet_arm["failures"],
        }), file=sys.stderr)
    return 0 if ok else 1


# -- trace-driven fleet autoscaler: the diurnal wave (--diurnal) ------------
#
# One fleet rides a low -> high (~10x) -> low concurrency wave three ways:
# "auto" starts at ONE replica with the FleetAutoscaler armed over a warm
# pool it can claim from; "static_small" is one replica forever (cheap,
# blows the latency band at the crest); "static_big" holds the crest-sized
# fleet all day (fast, pays peak chips through the trough). The win
# condition is the paper's elasticity claim: auto holds crest p95 TTFT in
# the big fleet's band while averaging well under the big fleet's chips,
# and every scale-down drains before it releases — zero failed streams
# end to end. A disagg sub-arm replays a long-prompt storm and checks the
# prefill tier grows while the decode tier does not.

DIURNAL_SLOTS = 4          # gateway admission capacity = 2x slots/replica
DIURNAL_PROMPT_BLOCKS = 8  # prompt length in full KV blocks
DIURNAL_DECODE_TOKENS = 32
DIURNAL_LOW = 1            # trough concurrency
DIURNAL_HIGH = 10          # the crest: ~10x the trough
DIURNAL_MAX_REPLICAS = 3


def _diurnal_prompt(nonce: int, vocab: int) -> list:
    """Unique per request (this arm runs WITHOUT a prefix cache): every
    arrival pays its full prefill, so TTFT degrades the moment the slots
    saturate — the latency signal the autoscaler closes the loop on."""
    return [3 + (nonce * 97 + i * 13) % (vocab - 4)
            for i in range(DIURNAL_PROMPT_BLOCKS * BLOCK_SIZE + 7)]


DIURNAL_STEP_FLOOR_S = 0.025
_PACED_CLS = None


def _paced_batcher_cls():
    """PagedBatcher with a wall-clock floor per engine step. On a TPU
    the step time is device-bound, so N replicas really are N× decode
    throughput; on a shared-CPU host N engine threads just steal each
    other's cores and a bigger 'fleet' gets SLOWER. The floor restores
    the property the experiment is about — each replica is a fixed-rate
    server — without touching the serving stack. The floor must
    dominate the real per-step compute (a few ms for the tiny model)
    by a wide margin, or a 1-core CI host oversubscribes and the
    biggest fleet measures slowest."""
    global _PACED_CLS
    if _PACED_CLS is None:
        from kubeflow_tpu.models.paged import PagedBatcher

        class _Paced(PagedBatcher):
            def _step(self):
                t0 = time.perf_counter()
                super()._step()
                left = DIURNAL_STEP_FLOOR_S - (time.perf_counter() - t0)
                if left > 0:
                    time.sleep(left)

        _PACED_CLS = _Paced
    return _PACED_CLS


def _make_diurnal_engine():
    from kubeflow_tpu.models.serving import GenerationConfig

    params, cfg = _load_model()
    bucket = (DIURNAL_PROMPT_BLOCKS + 1) * BLOCK_SIZE
    per_seq = -(-(bucket + DIURNAL_DECODE_TOKENS) // BLOCK_SIZE) + 1
    return _paced_batcher_cls()(
        params, cfg,
        gen=GenerationConfig(max_new_tokens=DIURNAL_DECODE_TOKENS,
                             eos_id=-1),
        slots=DIURNAL_SLOTS, num_blocks=DIURNAL_SLOTS * per_seq + 2,
        block_size=BLOCK_SIZE, prompt_bucket=bucket, prefix_cache=False,
    )


def _build_diurnal_telemetry(ttft_threshold_s: float):
    """Signals plane tuned to a minutes-long wave: 1s windows, 5s/15s
    fast burn windows so pressure both appears and clears within the
    run. TTFT is the only armed objective — its threshold comes from the
    arm's own measured quiet baseline, so the wave trips it on any host
    without hand-tuned absolute numbers. Queue wait stays inert on
    purpose: the replica-side p95 is a 256-sample deque, not
    time-windowed, so it would keep reporting crest pain long after the
    ebb and pin the fleet at peak size."""
    from kubeflow_tpu.observability.signals import (
        FleetTelemetry,
        SignalsConfig,
    )
    from kubeflow_tpu.observability.slo import default_objectives

    return FleetTelemetry(
        SignalsConfig(window_s=1.0, windows=120),
        objectives=default_objectives(
            ttft_p95_s=ttft_threshold_s, inter_token_p95_s=2.0,
            queue_wait_p95_s=5.0,
        ),
        slo_options={"fast_windows": (5.0, 10.0), "slow_window": 30.0,
                     "min_events": 6},
    )


def _diurnal_scaler_config():
    from kubeflow_tpu.models.autoscaler import AutoscalerConfig

    return AutoscalerConfig(
        min_replicas=1, max_replicas=DIURNAL_MAX_REPLICAS,
        up_consecutive=2, down_consecutive=5,
        up_cooldown_s=2.0, down_cooldown_s=3.0,
        max_actions_per_window=8, actions_window_s=60.0,
        drain_budget_s=30.0, stale_after_s=5.0,
        claim_backoff_base_s=0.5, claim_backoff_max_s=5.0,
    )


def _warm_pool_provisioner(gw, by_ep, pool, released):
    """In-process stand-in for the slice pool: scale-up claims a
    pre-started warm server for the tier and joins it to the ring; drain
    stops the victim off-thread (``stop()`` blocks until its in-flight
    streams finish — exactly the never-kill-a-stream contract); release
    records the slice as returned."""
    from kubeflow_tpu.models.autoscaler import WarmSliceProvisioner

    class _Pool(WarmSliceProvisioner):
        def scale_up(self, tier, now=None):
            warm = pool.get(tier) or []
            if not warm:
                return None
            ep = warm.pop(0)
            self.gateway.add_replica(ep)
            return ep

    def drain(ep):
        threading.Thread(target=by_ep[ep].stop, daemon=True).start()

    return _Pool(gw, drain_fn=drain, release_fn=released.append)


def _drive_diurnal_round(gw, conc: int, nonce_base: int, vocab: int,
                         outcomes: list, phase: str) -> None:
    threads = []
    for i in range(conc):
        prompt = _diurnal_prompt(nonce_base + i, vocab)

        def work(p=prompt, name=f"tenant-{i}"):
            ok, ttft, detail = _stream_once(
                gw, p, name, max_tokens=DIURNAL_DECODE_TOKENS)
            outcomes.append((phase, ok, ttft, detail))

        th = threading.Thread(target=work, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join()


def _chips_held(gw) -> int:
    """Slices the fleet is holding right now: in-ring replicas plus
    draining ones (a draining slice is out of the ring but not yet
    returned to the pool, so it still counts against the bill)."""
    draining = gw.stats()["autoscaler"]["draining"]
    return len(set(gw.ring_nodes()) | set(draining))


def run_diurnal_arm(kind: str, *, high: int, high_rounds: int,
                    low_rounds: int, settle_s: float) -> dict:
    """One pass of the wave against one fleet flavor. kind: "auto" =
    1 in-ring replica + a warm pool the autoscaler claims from;
    "static_small" = 1 replica, scaler inert; "static_big" =
    DIURNAL_MAX_REPLICAS replicas, scaler inert."""
    from kubeflow_tpu.models.gateway import ServingGateway
    from kubeflow_tpu.models.server import InferenceServer

    _, cfg = _load_model()
    vocab = cfg.vocab_size
    total = 1 if kind == "static_small" else DIURNAL_MAX_REPLICAS
    in_ring = 1 if kind == "auto" else total
    servers = [InferenceServer(_make_diurnal_engine(), port=0,
                               drain_s=60.0).start()
               for _ in range(total)]
    eps = [f"{s.host}:{s.port}" for s in servers]
    by_ep = dict(zip(eps, servers))
    released: list = []
    gw = ServingGateway(
        eps[:in_ring], port=0, block_size=BLOCK_SIZE,
        health_interval_s=0.1, reroute_budget=2,
        # The crest must reach the replicas as QUEUEING (the latency
        # signal), not as gateway-side tenant shed.
        max_inflight=4 * high,
        autoscaler_config=(_diurnal_scaler_config() if kind == "auto"
                           else None),
    ).start()
    if kind == "auto":
        gw.autoscaler.provisioner = _warm_pool_provisioner(
            gw, by_ep, {"fused": eps[in_ring:]}, released)
    outcomes: list = []
    chips: list = []
    try:
        # Calibration: quiet singles with telemetry detached (the scaler
        # stays frozen on "telemetry disabled") first pay the compiles,
        # then measure this host's healthy TTFT. The armed threshold is
        # a multiple of that baseline.
        warm: list = []
        for r in range(2):
            _drive_diurnal_round(gw, 1, 900_000 + r, vocab, warm, "warm")
        calib: list = []
        for r in range(3):
            _drive_diurnal_round(gw, 1, 910_000 + r, vocab, calib,
                                 "calib")
        bad = [d for _, ok, _, d in warm + calib if not ok]
        if bad:
            raise RuntimeError(f"{kind} calibration failures: {bad}")
        baseline = max(t for _, _, t, _ in calib)
        threshold = max(3.0 * baseline, baseline + 0.15)
        telemetry = _build_diurnal_telemetry(threshold)
        gw.telemetry = telemetry
        gw._tenant_buckets = telemetry.tenants

        # Chips are sampled on the wall clock (not per round — rounds
        # have different durations at different fleet sizes), so the
        # mean is a time-weighted slice bill.
        stop_sampling = threading.Event()

        def sample():
            while not stop_sampling.wait(0.2):
                chips.append(_chips_held(gw) if kind == "auto"
                             else total)

        sampler = threading.Thread(target=sample, daemon=True)
        sampler.start()

        t0 = time.perf_counter()
        nonce = 0
        for phase, conc, rounds in (("low", DIURNAL_LOW, low_rounds),
                                    ("high", high, high_rounds),
                                    ("ebb", DIURNAL_LOW, low_rounds)):
            for r in range(rounds):
                # The crest's second half is the steady state the band
                # gate reads; the first half (scale-up in flight) stays
                # in the artifact as the adaptation transient.
                tag = ("high_steady"
                       if phase == "high" and r >= rounds // 2
                       else phase)
                _drive_diurnal_round(gw, conc, nonce, vocab, outcomes,
                                     tag)
                nonce += conc
        # Ebb settle (auto only): keep trough traffic flowing until the
        # burn windows clear, the drains finish, and the fleet is back
        # to one slice — or the settle budget expires.
        deadline = time.perf_counter() + settle_s
        while kind == "auto" and time.perf_counter() < deadline:
            st = gw.stats()["autoscaler"]
            if (released and not st["draining"]
                    and sum(st["tier_replicas"].values()) == 1):
                break
            _drive_diurnal_round(gw, DIURNAL_LOW, nonce, vocab,
                                 outcomes, "ebb")
            nonce += DIURNAL_LOW
            time.sleep(0.2)
        # The rest of the night: the trough resumes after the wave, so
        # the time-weighted bill reflects a day that is mostly trough —
        # not a run that ends the moment the last slice is released.
        for _ in range(2 * low_rounds):
            _drive_diurnal_round(gw, DIURNAL_LOW, nonce, vocab,
                                 outcomes, "ebb")
            nonce += DIURNAL_LOW
        wall = time.perf_counter() - t0
        stop_sampling.set()
        sampler.join(timeout=2.0)

        failures = [d for _, ok, _, d in outcomes if not ok]

        def p95(*phases):
            vals = [t for ph, ok, t, _ in outcomes
                    if ph in phases and ok]
            return _p95_ms(vals) if vals else 0.0

        scaler = (gw.stats()["autoscaler"] if kind == "auto"
                  else {"enabled": False})
        return {
            "kind": kind,
            "requests_completed": sum(
                1 for _, ok, _, _ in outcomes if ok),
            "failures": failures,
            "ttft_threshold_ms": round(threshold * 1e3, 2),
            "low_p95_ttft_ms": p95("low"),
            "high_p95_ttft_ms": p95("high", "high_steady"),
            "high_steady_p95_ttft_ms": p95("high_steady"),
            "ebb_p95_ttft_ms": p95("ebb"),
            "chips_mean": round(sum(chips) / max(len(chips), 1), 3),
            "chips_peak": max(chips) if chips else 0,
            "chips_steady": min(chips) if chips else 0,
            "wall_s": round(wall, 2),
            "released": list(released),
            "autoscaler": scaler,
            "decisions": (gw.autoscaler.debug()["decisions"][-40:]
                          if kind == "auto" else []),
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()


def run_diurnal_disagg_arm(*, storm_conc: int, max_storm_rounds: int
                           ) -> dict:
    """Long-prompt storm against a disagg fleet with the scaler armed:
    TTFT burn is a PREFILL-tier objective, so the storm must grow the
    prefill tier only — the decode tier holds (its inter-token signal
    stays quiet, and min_replicas stops its ebb)."""
    from kubeflow_tpu.models.gateway import ServingGateway
    from kubeflow_tpu.models.server import InferenceServer

    _, cfg = _load_model()
    vocab = cfg.vocab_size
    roles = ["prefill", "decode", "prefill", "decode"]
    servers = [InferenceServer(_make_disagg_engine(), port=0,
                               drain_s=60.0, tier_role=role).start()
               for role in roles]
    eps = [f"{s.host}:{s.port}" for s in servers]
    by_ep = dict(zip(eps, servers))
    released: list = []
    gw = ServingGateway(
        eps[:2], port=0, block_size=BLOCK_SIZE, health_interval_s=0.1,
        reroute_budget=2, max_inflight=4 * storm_conc,
        tier_mode="disagg", tier_roles=dict(zip(eps, roles)),
        autoscaler_config=_diurnal_scaler_config(),
    ).start()
    gw.autoscaler.provisioner = _warm_pool_provisioner(
        gw, by_ep, {"prefill": [eps[2]], "decode": [eps[3]]}, released)
    outcomes: list = []
    short_len = DISAGG_SHORT_TOKENS
    long_len = DISAGG_LONG_BLOCKS * BLOCK_SIZE + 3

    def drive(conc, nonce_base, length, phase, into=None):
        threads = []
        for i in range(conc):
            prompt = _disagg_prompt(nonce_base + i, length, vocab)

            def work(p=prompt, name=f"tenant-{i}"):
                ok, ttft, detail = _stream_once(
                    gw, p, name, max_tokens=DISAGG_DECODE_TOKENS)
                (outcomes if into is None else into).append(
                    (phase, ok, ttft, detail))

            th = threading.Thread(target=work, daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join()

    try:
        # Warm both prompt shapes and the KV handoff, then calibrate the
        # TTFT threshold on quiet short singles.
        setup: list = []
        drive(2, 800_000, long_len, "warm", into=setup)
        drive(2, 810_000, short_len, "warm", into=setup)
        calib: list = []
        for r in range(3):
            drive(1, 820_000 + r, short_len, "calib", into=calib)
        bad = [d for _, ok, _, d in setup + calib if not ok]
        if bad:
            raise RuntimeError(f"disagg calibration failures: {bad}")
        baseline = max(t for _, _, t, _ in calib)
        threshold = max(3.0 * baseline, baseline + 0.15)
        telemetry = _build_diurnal_telemetry(threshold)
        gw.telemetry = telemetry
        gw._tenant_buckets = telemetry.tenants

        rounds_run = 0
        for r in range(max_storm_rounds):
            drive(storm_conc, r * storm_conc, long_len, "storm")
            rounds_run += 1
            sizes = gw.stats()["autoscaler"]["tier_replicas"]
            if sizes.get("prefill", 0) >= 2:
                break
        gw.probe_once()
        st = gw.stats()["autoscaler"]
        decisions = gw.autoscaler.debug()["decisions"]
        ups = [d for d in decisions if d["action"] == "scale_up"]
        failures = [d for _, ok, _, d in outcomes if not ok]
        return {
            "storm_rounds": rounds_run,
            "requests_completed": sum(
                1 for _, ok, _, _ in outcomes if ok),
            "failures": failures,
            "ttft_threshold_ms": round(threshold * 1e3, 2),
            "tier_replicas": st["tier_replicas"],
            "scale_up_tiers": sorted({d["tier"] for d in ups}),
            "prefill_grew": st["tier_replicas"].get("prefill", 0) >= 2,
            "decode_held": st["tier_replicas"].get("decode", 0) == 1,
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()


def main_diurnal(args) -> int:
    """--diurnal: the autoscaler elasticity experiment. --smoke runs the
    auto arm only on a shrunk wave (gate: >=1 scale-up, >=1 drained
    release, zero failed streams); live runs all three arms plus the
    disagg storm and writes SERVE_r11_autoscale.json."""
    if args.smoke:
        wave = dict(high=DIURNAL_HIGH, high_rounds=5, low_rounds=2,
                    settle_s=45.0)
        print("# diurnal smoke: auto arm only ...", file=sys.stderr)
        auto = run_diurnal_arm("auto", **wave)
        summary = {
            "auto_scale_ups": auto["autoscaler"]["scale_ups"],
            "auto_releases": len(auto["released"]),
            "auto_failures": len(auto["failures"]),
            "auto_holds": auto["autoscaler"]["holds"],
            "auto_freezes": auto["autoscaler"]["freezes"],
            "auto_ttft_threshold_ms": auto["ttft_threshold_ms"],
            "auto_high_p95_ttft_ms": auto["high_p95_ttft_ms"],
            "auto_chips_peak": auto["chips_peak"],
        }
        print(json.dumps(summary))
        ok = (not auto["failures"]
              and auto["autoscaler"]["scale_ups"] >= 1
              and len(auto["released"]) >= 1
              and not auto["autoscaler"]["draining"])
        print("# --smoke: artifact write and win gate skipped",
              file=sys.stderr)
        return 0 if ok else 1

    wave = dict(high=DIURNAL_HIGH, high_rounds=8, low_rounds=10,
                settle_s=60.0)
    arms = {}
    for kind in ("auto", "static_small", "static_big"):
        print(f"# diurnal {kind} arm (fresh fleet) ...", file=sys.stderr)
        arms[kind] = run_diurnal_arm(kind, **wave)
    print("# diurnal disagg storm (prefill-only growth) ...",
          file=sys.stderr)
    disagg = run_diurnal_disagg_arm(storm_conc=4, max_storm_rounds=12)

    auto, small, big = (arms["auto"], arms["static_small"],
                        arms["static_big"])
    # The latency band the crest must hold: the static crest-sized
    # fleet's own p95, plus slack for scale-up transients.
    band_ms = max(1.5 * big["high_p95_ttft_ms"],
                  big["high_p95_ttft_ms"] + 100.0)
    record = {
        "scenario": (
            f"diurnal wave {DIURNAL_LOW}->{wave['high']}->{DIURNAL_LOW} "
            f"concurrent streams over {DIURNAL_SLOTS}-slot replicas; "
            "auto = 1 replica + warm pool under the trace-driven "
            f"autoscaler (max {DIURNAL_MAX_REPLICAS}), statics pinned"
        ),
        "model": "tiny",
        "provenance": "live",
        "host": _record_host(),
        "mesh": {"tp": 1},  # single-chip replicas
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
        "band_ms": round(band_ms, 2),
        "arms": arms,
        "disagg_storm": disagg,
    }
    summary = {
        "auto_high_p95_ttft_ms": auto["high_p95_ttft_ms"],
        "auto_high_steady_p95_ttft_ms": auto["high_steady_p95_ttft_ms"],
        "small_high_p95_ttft_ms": small["high_p95_ttft_ms"],
        "big_high_p95_ttft_ms": big["high_p95_ttft_ms"],
        "band_ms": round(band_ms, 2),
        "auto_chips_mean": auto["chips_mean"],
        "big_chips_mean": big["chips_mean"],
        "auto_scale_ups": auto["autoscaler"]["scale_ups"],
        "auto_releases": len(auto["released"]),
        "failures": sum(len(a["failures"]) for a in arms.values()),
        "disagg_prefill_grew": disagg["prefill_grew"],
        "disagg_decode_held": disagg["decode_held"],
    }
    print(json.dumps(summary))
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(f"# wrote {args.out}", file=sys.stderr)
    win = (
        all(not a["failures"] for a in arms.values())
        and not disagg["failures"]
        # Elasticity: the scaler rode the wave up AND back down.
        and auto["autoscaler"]["scale_ups"] >= 1
        and len(auto["released"]) >= 1
        and auto["chips_steady"] == 1
        # The crest: once adapted, auto holds the big fleet's latency
        # band; the trough-sized static fleet blows it. (The adaptation
        # transient stays visible in high_p95_ttft_ms.)
        and auto["high_steady_p95_ttft_ms"] <= band_ms
        and small["high_p95_ttft_ms"] > band_ms
        # The bill: auto averages well under the crest-sized fleet.
        and auto["chips_mean"] <= 0.75 * big["chips_mean"]
        # Disagg: a long-prompt storm grows the prefill tier only.
        and disagg["prefill_grew"] and disagg["decode_held"]
        and disagg["scale_up_tiers"] == ["prefill"]
    )
    if not win:
        print("# r11 win gate FAILED", file=sys.stderr)
    return 0 if win else 1


# -- fleet KV tier ring-churn arm (--ring-churn) ------------------------

# Each tenant's shared chain, in full KV blocks. Long enough that the
# chain's re-prefill dominates the peer path's fixed costs (three
# loopback hops + the per-block import writes) — the same reasoning as
# PREFIX_BLOCKS above, and the same length.
CHURN_PREFIX_BLOCKS = 16
CHURN_TAIL_TOKENS = 5      # unique per-request suffix
CHURN_DECODE_TOKENS = 6
CHURN_TENANTS = 6          # per churn cycle: stayers + movers
CHURN_MOVERS = 2           # tenants whose owner the join steals
CHURN_CYCLES = 2
CHURN_SLOTS = 2


def _churn_prefix(seed: int, vocab: int) -> list:
    """One tenant's shared chain (full blocks only, deterministic)."""
    n = CHURN_PREFIX_BLOCKS * BLOCK_SIZE
    return [3 + (seed * 389 + i * 11) % (vocab - 4) for i in range(n)]


def _churn_tail(nonce: int, vocab: int) -> list:
    return [3 + (nonce * 29 + i * 13) % (vocab - 4)
            for i in range(CHURN_TAIL_TOKENS)]


def _make_churn_engine():
    from kubeflow_tpu.models.paged import PagedBatcher
    from kubeflow_tpu.models.serving import GenerationConfig

    params, cfg = _load_model()
    prompt_len = CHURN_PREFIX_BLOCKS * BLOCK_SIZE + CHURN_TAIL_TOKENS
    per_seq = -(-(prompt_len + CHURN_DECODE_TOKENS) // BLOCK_SIZE) + 1
    # Every cycle's tenant chains must stay resident fleet-wide (plus
    # the export/import warm chain), or an evicted donor chain turns a
    # peer fetch into chain_gone noise.
    chains = (CHURN_CYCLES * CHURN_TENANTS + 1) * (CHURN_PREFIX_BLOCKS + 2)
    return PagedBatcher(
        params, cfg,
        gen=GenerationConfig(max_new_tokens=CHURN_DECODE_TOKENS,
                             eos_id=-1),
        slots=CHURN_SLOTS, num_blocks=CHURN_SLOTS * per_seq + chains + 2,
        block_size=BLOCK_SIZE,
        prompt_bucket=(CHURN_PREFIX_BLOCKS + 1) * BLOCK_SIZE,
        prefix_cache=True,
    )


def _build_churn_fleet(peer_fanout: int):
    """3 fused in-ring replicas + 1 started standby (out of the ring
    until the join event). The probe loop is parked far out — churn is
    driven explicitly via add_replica/remove_replica, and an in-process
    probe racing a JIT compile must not reshuffle the ring mid-round."""
    from kubeflow_tpu.models.gateway import ServingGateway, \
        prompt_chain_keys
    from kubeflow_tpu.models.server import InferenceServer

    _, cfg = _load_model()
    engines = [_make_churn_engine() for _ in range(4)]
    # Compile the export/import shapes (per-block device<->host copies)
    # before the engines go behind servers: the first measured peer
    # fetch must pay transfer cost, not compile cost. The jit cache is
    # process-wide, so only the first fleet of the run pays anything.
    warm = _churn_prefix(7, cfg.vocab_size) + _churn_tail(0, cfg.vocab_size)
    engines[0].submit(warm, max_new_tokens=1)
    engines[0].run()
    payload = engines[0].export_chain(
        prompt_chain_keys(warm, BLOCK_SIZE))
    engines[1].import_chain(payload, warm)
    servers = [
        InferenceServer(eng, port=0, drain_s=2.0).start()
        for eng in engines
    ]
    eps = [f"{s.host}:{s.port}" for s in servers]
    gw = ServingGateway(
        eps[:3], port=0, affinity="prefix", block_size=BLOCK_SIZE,
        health_interval_s=30.0, reroute_budget=2,
        kv_peer_fanout=peer_fanout,
    ).start()
    gw.probe_once()
    return gw, servers, eps, cfg


def _stream_ttft(gw, prompt, tenant: str, timeout: float = 120.0):
    """One streaming completion; returns (ok, ttft_seconds, detail).
    TTFT — request start to first SSE data line at the client — is the
    cost a peer fetch must beat re-prefill on."""
    conn = http.client.HTTPConnection(gw.host, gw.port, timeout=timeout)
    try:
        t0 = time.perf_counter()
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": prompt, "stream": True,
                        "max_tokens": CHURN_DECODE_TOKENS,
                        "user": tenant}).encode(),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        if resp.status != 200:
            return False, 0.0, f"HTTP {resp.status}"
        ttft = None
        finished = False
        error = None
        while True:
            line = resp.fp.readline()
            if not line:
                break
            if not line.startswith(b"data:"):
                continue
            if line == b"data: [DONE]\n":
                finished = True
                break
            if b'"error"' in line:
                error = line.decode().strip()
                continue
            if ttft is None:
                ttft = time.perf_counter() - t0
        if not finished or error or ttft is None:
            return False, 0.0, error or "truncated stream"
        return True, ttft, ""
    except OSError as err:
        return False, 0.0, str(err)
    finally:
        conn.close()


def _churn_pick_tenants(gw, standby_ep: str, vocab: int, seed0: int):
    """Tenant chains for one cycle, split by what the standby's join
    does to their route: ``movers`` are stolen by the standby,
    ``stayers`` keep their owner. Ownership is read off the REAL ring
    (tentative add/remove), so the split can never drift from routing;
    the prefix router learns a chain on first sight, so each candidate
    is keyed twice and judged on the stable key."""
    movers, stayers = [], []
    want_stay = CHURN_TENANTS - CHURN_MOVERS
    seed = seed0
    while len(movers) < CHURN_MOVERS or len(stayers) < want_stay:
        seed += 1
        if seed > seed0 + 500:
            raise RuntimeError("no ring split found for churn tenants")
        prefix = _churn_prefix(seed, vocab)
        gw._route_key(prefix)
        key = gw._route_key(prefix)
        before = gw._candidates(key)
        gw.add_replica(standby_ep)
        after = gw._candidates(key)
        gw.remove_replica(standby_ep)
        if not before or not after:
            continue
        if after[0] == standby_ep and len(movers) < CHURN_MOVERS:
            movers.append(prefix)
        elif after[0] == before[0] and len(stayers) < want_stay:
            stayers.append(prefix)
    return movers, stayers


def _fleet_prefix_counters(servers) -> tuple:
    hits = sum(s.engine.prefix_hits for s in servers)
    misses = sum(s.engine.prefix_misses for s in servers)
    return hits, misses


def run_churn_arm(mode: str, *, cycles: int) -> dict:
    """mode="static": no churn, the prefix-hit-ratio baseline.
    mode="peer": join/leave churn with the peer-fetch tier armed.
    mode="noPeer": the same churn, fanout=0 — every moved chain
    re-prefills from scratch."""
    fanout = 2 if mode == "peer" else 0
    gw, servers, eps, cfg = _build_churn_fleet(fanout)
    standby = eps[3]
    vocab = cfg.vocab_size
    nonce = iter(range(1, 1 << 20))
    outcomes: list = []
    moved_ttfts: list = []
    measured_hits = measured_misses = 0

    def drive(prompts, bucket=None):
        nonlocal measured_hits, measured_misses
        h0, m0 = _fleet_prefix_counters(servers)
        for i, prefix in enumerate(prompts):
            prompt = prefix + _churn_tail(next(nonce), vocab)
            ok, ttft, detail = _stream_ttft(gw, prompt, f"tenant-{i}")
            outcomes.append((ok, detail))
            if bucket is not None and ok:
                bucket.append(ttft)
        h1, m1 = _fleet_prefix_counters(servers)
        measured_hits += h1 - h0
        measured_misses += m1 - m0

    telemetry = _build_telemetry()
    try:
        for cycle in range(cycles):
            movers, stayers = _churn_pick_tenants(
                gw, standby, vocab, seed0=1000 * (cycle + 1))
            tenants = movers + stayers
            # Warm pass 1 registers each chain (full prefill); pass 2
            # confirms residency and compiles the full-hit suffix shape.
            # Neither is measured.
            for prefix in tenants:
                _stream_ttft(gw, prefix + _churn_tail(next(nonce), vocab),
                             "warm")
            for prefix in tenants:
                _stream_ttft(gw, prefix + _churn_tail(next(nonce), vocab),
                             "warm")
            if cycle == 0:
                gw.telemetry = telemetry
                gw._tenant_buckets = telemetry.tenants
            drive(tenants)                      # steady, warm ring
            if mode == "static":
                drive(tenants)
                continue
            gw.add_replica(standby)             # JOIN: movers go cold
            drive(movers, bucket=moved_ttfts)   # the gated TTFT sample
            drive(stayers)
            drive(tenants)                      # steady on the 4-ring
            gw.remove_replica(standby)          # LEAVE: back to warm owners
            drive(tenants)
        gw.probe_once()
        stats = gw.stats()
        signals = _debug_json(gw, "/debug/signals")
        failures = [d for ok, d in outcomes if not ok]
        total = measured_hits + measured_misses
        return {
            "mode": mode,
            "requests_completed": sum(1 for ok, _ in outcomes if ok),
            "failures": failures,
            "prefix_hit_ratio": round(measured_hits / max(total, 1), 4),
            "prefix_hits": measured_hits,
            "prefix_misses": measured_misses,
            "moved_ttft_p95_ms": (_p95_ms(moved_ttfts)
                                  if moved_ttfts else None),
            "moved_requests": len(moved_ttfts),
            "kv_peer": {
                "fetches": stats["kv_peer_fetches"],
                "fetch_failures": stats["kv_peer_fetch_failures"],
                "bytes": stats["kv_peer_bytes"],
                "fetch_latency_s": stats["kv_peer_fetch_latency_s"],
                "max_bytes": stats["kv_peer"]["max_bytes"],
                "failure_reasons": stats["kv_peer"]["failure_reasons"],
            },
            "signals_kv_peer_fetch_s": (signals.get("fleet") or {}).get(
                "kv_peer_fetch_s"),
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()


def main_ring_churn(args) -> int:
    """--ring-churn: replicas join and leave mid-run. The peer-fetch
    tier must hold the fleet prefix hit ratio within 10% of a static
    ring's, beat re-prefill on moved-chain p95 TTFT, keep every fetch
    under the byte cap, and fail nothing — while the fanout=0 control
    shows what churn costs without it."""
    global CHURN_PREFIX_BLOCKS, CHURN_TENANTS, CHURN_MOVERS
    cycles = CHURN_CYCLES
    if args.smoke:
        CHURN_PREFIX_BLOCKS, CHURN_TENANTS, CHURN_MOVERS = 4, 4, 1
        cycles = 1
    print(f"# ring-churn static baseline: 3 replicas, "
          f"{CHURN_TENANTS} tenants x {cycles} cycles ...",
          file=sys.stderr)
    static = run_churn_arm("static", cycles=cycles)
    print("# ring-churn peer arm: join/leave churn, kv_peer_fanout=2 "
          "...", file=sys.stderr)
    peer = run_churn_arm("peer", cycles=cycles)
    print("# ring-churn control arm: same churn, peer tier off ...",
          file=sys.stderr)
    no_peer = run_churn_arm("noPeer", cycles=cycles)

    record = {
        "scenario": (
            f"{CHURN_TENANTS} tenants with {CHURN_PREFIX_BLOCKS}-block "
            "shared chains over 3 prefix-cached fused replicas; a "
            "standby joins and leaves the ring each cycle, moving "
            f"{CHURN_MOVERS} tenants' chains — peer prefix fetch vs "
            "re-prefill vs a static ring"
        ),
        "model": "tiny",
        "block_size": BLOCK_SIZE,
        "prefix_blocks": CHURN_PREFIX_BLOCKS,
        "tenants": CHURN_TENANTS,
        "movers": CHURN_MOVERS,
        "cycles": cycles,
        "provenance": "smoke" if args.smoke else "live",
        "host": _record_host(),
        "mesh": {"tp": 1},  # single-chip replicas
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "static": static,
        "peer": peer,
        "no_peer": no_peer,
    }
    print(json.dumps({
        "static_hit_ratio": static["prefix_hit_ratio"],
        "peer_hit_ratio": peer["prefix_hit_ratio"],
        "no_peer_hit_ratio": no_peer["prefix_hit_ratio"],
        "peer_moved_p95_ttft_ms": peer["moved_ttft_p95_ms"],
        "no_peer_moved_p95_ttft_ms": no_peer["moved_ttft_p95_ms"],
        "kv_peer_fetches": peer["kv_peer"]["fetches"],
        "kv_peer_fetch_failures": peer["kv_peer"]["fetch_failures"],
        "kv_peer_bytes": peer["kv_peer"]["bytes"],
    }))
    clean = (
        not static["failures"] and not peer["failures"]
        and not no_peer["failures"]
        and peer["kv_peer"]["fetches"] >= 1
        and peer["kv_peer"]["fetch_failures"] == 0
        and no_peer["kv_peer"]["fetches"] == 0
        and peer["kv_peer"]["bytes"]
        <= peer["kv_peer"]["fetches"] * peer["kv_peer"]["max_bytes"]
    )
    if not clean:
        print("# ring-churn gate FAILED: " + json.dumps({
            "failures": {"static": static["failures"],
                         "peer": peer["failures"],
                         "no_peer": no_peer["failures"]},
            "kv_peer": peer["kv_peer"],
            "no_peer_fetches": no_peer["kv_peer"]["fetches"],
        }), file=sys.stderr)
    if args.smoke:
        print("# --smoke: artifact write and win gate skipped",
              file=sys.stderr)
        return 0 if clean else 1
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(f"# wrote {args.out}", file=sys.stderr)
    ratio_held = (peer["prefix_hit_ratio"]
                  >= 0.9 * static["prefix_hit_ratio"])
    ttft_wins = (peer["moved_ttft_p95_ms"] is not None
                 and no_peer["moved_ttft_p95_ms"] is not None
                 and peer["moved_ttft_p95_ms"]
                 < no_peer["moved_ttft_p95_ms"])
    win = clean and ratio_held and ttft_wins
    if not win:
        print("# win gate: " + json.dumps({
            "hit_ratio_within_10pct": ratio_held,
            "peer_beats_reprefill_ttft": ttft_wins,
        }), file=sys.stderr)
    return 0 if win else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--tenants", type=int, default=6)
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--churn-rounds", type=int, default=6)
    ap.add_argument("--disagg", action="store_true",
                    help="run the disaggregated prefill/decode tier "
                         "experiment instead of affinity-vs-random "
                         "(artifact: SERVE_r08_disagg.json)")
    ap.add_argument("--evict-storm", action="store_true",
                    help="run the HBM-economy eviction storm: bf16 "
                         "evict+re-prefill vs int8 KV + host-RAM swap "
                         "(artifact: SERVE_r09_hbm.json)")
    ap.add_argument("--spec", action="store_true",
                    help="run the speculative-decoding decode bench: "
                         "ragged spec scheduling vs plain ragged, "
                         "token-exact (artifact: SERVE_r10_spec.json)")
    ap.add_argument("--multilora", action="store_true",
                    help="run the 64-adapter multi-LoRA fleet: (prefix, "
                         "adapter) affinity vs adapter-oblivious routing "
                         "(artifact: SERVE_r10_spec.json)")
    ap.add_argument("--diurnal", action="store_true",
                    help="run the fleet-autoscaler diurnal wave: auto "
                         "(1 replica + warm pool, scaler armed) vs "
                         "static small/big fleets, plus a disagg "
                         "long-prompt storm "
                         "(artifact: SERVE_r11_autoscale.json)")
    ap.add_argument("--tp", action="store_true",
                    help="run the tensor-parallel replica experiment: "
                         "ONE tp=4 mesh replica (head-sharded block "
                         "pool, one HTTP endpoint) vs a fleet of 4 "
                         "single-chip replicas, token-exact "
                         "(artifact: SERVE_r13_tp.json)")
    ap.add_argument("--ring-churn", action="store_true",
                    help="run the fleet-KV-tier churn experiment: "
                         "replicas join/leave mid-run, peer prefix "
                         "fetch vs re-prefill vs a static ring "
                         "(artifact: SERVE_r12_peerkv.json)")
    ap.add_argument("--smoke", action="store_true",
                    help="2 replicas x 2 tenants x 2 rounds, no artifact, "
                         "no win gate — CI executability tier")
    args = ap.parse_args()
    root = Path(__file__).resolve().parent.parent
    if args.out is None:
        args.out = str(root / (
            "SERVE_r13_tp.json" if args.tp
            else "SERVE_r12_peerkv.json" if args.ring_churn
            else "SERVE_r11_autoscale.json" if args.diurnal
            else "SERVE_r10_spec.json" if args.spec or args.multilora
            else "SERVE_r09_hbm.json" if args.evict_storm
            else "SERVE_r08_disagg.json" if args.disagg
            else "SERVE_r07_fleet.json"))
    if args.tp:
        return main_tp(args)
    if args.ring_churn:
        return main_ring_churn(args)
    if args.diurnal:
        return main_diurnal(args)
    if args.spec or args.multilora:
        return main_spec(args)
    if args.evict_storm:
        return main_evict(args)
    if args.disagg:
        return main_disagg(args)
    if args.smoke:
        global PREFIX_BLOCKS
        args.replicas, args.tenants = 2, 2
        args.rounds = args.churn_rounds = 2
        PREFIX_BLOCKS = 2  # executability tier: skip the long compiles

    wcb = -(-args.tenants // args.replicas) * PREFIX_BLOCKS
    kw = dict(replicas=args.replicas, tenants=args.tenants,
              rounds=args.rounds, warm_chain_blocks=wcb)
    print("# warming prefill/decode shapes ...", file=sys.stderr)
    _warm_shapes(wcb)
    print(f"# affinity arm: {args.replicas} replicas, {args.tenants} "
          f"tenants x {args.rounds} rounds ...", file=sys.stderr)
    affinity = run_arm("prefix", **kw)
    print(f"# random arm (fresh fleet) ...", file=sys.stderr)
    random_arm = run_arm("random", **kw)
    print("# churn phase: join + drain mid-run ...", file=sys.stderr)
    churn = run_churn(tenants=args.tenants, rounds=args.churn_rounds,
                      warm_chain_blocks=wcb)
    # Floor: the two measured arms' completions (warm-up and churn
    # completions only push the chain count higher).
    trace_summary = _verify_trace_export(
        affinity["requests_completed"] + random_arm["requests_completed"]
    )

    speedup = round(
        affinity["requests_per_sec"]
        / max(random_arm["requests_per_sec"], 1e-9), 3)
    record = {
        "scenario": (
            f"{args.tenants} tenants with {PREFIX_BLOCKS}-block shared "
            f"system prompts over {args.replicas} prefix-cached replicas; "
            "per-replica block pool holds only its fair share of warm "
            "chains"
        ),
        "model": "tiny",
        "replicas": args.replicas,
        "tenants": args.tenants,
        "rounds": args.rounds,
        "block_size": BLOCK_SIZE,
        "prefix_blocks": PREFIX_BLOCKS,
        "provenance": "smoke" if args.smoke else "live",
        "host": _record_host(),
        "mesh": {"tp": 1},  # single-chip replicas
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "affinity": affinity,
        "random": random_arm,
        "churn": churn,
        "throughput_speedup": speedup,
        **({"trace_summary": trace_summary} if trace_summary else {}),
    }
    print(json.dumps({
        "affinity_rps": affinity["requests_per_sec"],
        "random_rps": random_arm["requests_per_sec"],
        "throughput_speedup": speedup,
        "affinity_p95_ttft_ms": affinity["p95_ttft_ms"],
        "random_p95_ttft_ms": random_arm["p95_ttft_ms"],
        "affinity_hit_ratio": affinity["prefix_cache"]["hit_ratio"],
        "random_hit_ratio": random_arm["prefix_cache"]["hit_ratio"],
        "churn_failures": len(churn["failures"]),
        "telemetry_ttft_p95_ms": affinity["signals"]["ttft_p95_ms"],
        "slo_breaches": (affinity["slo"]["breaches_total"]
                         + random_arm["slo"]["breaches_total"]),
    }))
    # SLO gate: a healthy run must report ZERO breaches, and the
    # telemetry plane's TTFT p95 must agree with the clients' own
    # measurement — otherwise the autoscaler's future input is lying.
    slo_clean = all(
        arm["signals"]["agrees_within_15pct"]
        and arm["slo"]["breaches_total"] == 0
        and not arm["slo"]["breaching"]
        for arm in (affinity, random_arm)
    )
    if not slo_clean:
        print("# SLO gate FAILED: "
              + json.dumps({
                  "affinity": {**affinity["signals"], **affinity["slo"]},
                  "random": {**random_arm["signals"],
                             **random_arm["slo"]},
              }), file=sys.stderr)
    clean = (
        not affinity["failures"] and not random_arm["failures"]
        and not churn["failures"] and churn["ring_converged"]
        and slo_clean
    )
    if args.smoke:
        # Executability proven; toy numbers must not persist where a
        # scoreboard could mistake them for a measurement.
        print("# --smoke: artifact write and win gate skipped",
              file=sys.stderr)
        return 0 if clean else 1
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(f"# wrote {args.out}", file=sys.stderr)
    win = (
        clean
        and speedup >= 1.2
        and affinity["p95_ttft_ms"] <= random_arm["p95_ttft_ms"]
    )
    return 0 if win else 1


if __name__ == "__main__":
    sys.exit(main())
