#!/usr/bin/env python
"""Fleet serving load test: prefix-affinity vs random routing.

Drives the SAME multi-tenant workload (every tenant opens with its own
shared system prompt — several full KV blocks — followed by a unique
per-request tail) through two fresh fleets of real
``InferenceServer`` replicas over ``PagedBatcher(prefix_cache=True)``
tiny models, fronted by ``ServingGateway``:

- ``affinity``: consistent-hash routing on the prompt's longest shared
  prefix chain key — every tenant's traffic lands on the replica whose
  block pool already holds its system prompt, so admissions skip the
  shared blocks' prefill;
- ``random``: uniform spread — each replica keeps re-prefilling (and,
  under block-pool pressure, re-evicting) every tenant's prefix.

Each replica's block pool is sized to hold only ~tenants/replicas warm
chains beyond its active slots: the fleet CAN cache every tenant's
prefix collectively, but no single replica can cache all of them — the
capacity argument for affinity routing.

Per-request TTFT is the wall-clock to the first SSE token through the
gateway; throughput is completed requests over the measured wall time.
Both arms get warm-up rounds at identical shapes so compile time never
lands in the measured numbers. Prefix hit/miss/eviction counts are the
engines' own counters (the same numbers the gateway scrapes from
``/stats`` and Prometheus exports as
``tpu_serving_prefix_cache_*_total``), measured as deltas across the
timed phase.

A separate churn phase then proves elasticity on a live fleet: a third
replica joins mid-run and a drained replica leaves mid-run, with zero
failed (non-re-routed) requests end to end.

Each measured arm also runs the fleet telemetry plane
(``observability/signals.py``) and queries it over HTTP: the run gates
on ``/debug/signals`` TTFT p95 agreeing with the clients' own stopwatch
(±15%, small absolute floor) and on ``/debug/slo`` reporting ZERO
breaches for a healthy fleet — the SLO gate. Both summaries are stamped
into the artifact.

The artifact (default SERVE_r07_fleet.json, written atomically) records
both arms; the win condition is affinity throughput ≥ 1.2× random at a
p95 TTFT no worse than random's, with zero churn failures.

``--smoke`` shrinks to 2 replicas × 2 tenants × 2 rounds on the tiny
model, skips the artifact and the win gate (executability only) — the
integration-workflow tier.

Sibling experiments share the harness: ``--disagg`` (prefill/decode
tier split, SERVE_r08_disagg.json), ``--evict-storm`` (HBM economy:
bf16 evict+re-prefill vs int8 KV + host-RAM swap on one byte budget,
SERVE_r09_hbm.json), and ``--spec`` / ``--multilora`` (speculative
decoding as a ragged scheduling mode, token-exact vs plain; 64-adapter
multi-LoRA fleet with (prefix, adapter) affinity vs adapter-oblivious
routing — both into SERVE_r10_spec.json).

Usage: python loadtest/serve_fleet.py [--out SERVE_r07_fleet.json]
       [--replicas 3] [--tenants 6] [--rounds 6] [--smoke]
       [--disagg | --evict-storm | --spec --multilora]
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BLOCK_SIZE = 16
# Shared system prompt length in full KV blocks. Long enough that the
# prompt's prefill dominates per-request compute — the work a prefix-
# cache hit skips. --smoke shrinks it (module global, set once in main).
PREFIX_BLOCKS = 16
TAIL_TOKENS = 15           # unique per-request suffix
DECODE_TOKENS = 4


def _p95_ms(values) -> float:
    """Nearest-rank p95 in milliseconds — ONE formula for every artifact
    field, so the affinity and random numbers can never drift."""
    return round(sorted(values)[max(0, int(0.95 * len(values)) - 1)] * 1e3, 2)


def _tenant_prompt(tenant: int, nonce: int, vocab: int) -> list:
    """System prompt shared by ALL of a tenant's requests + a unique
    tail. Deterministic (no RNG): token ids are arithmetic in a band per
    tenant, far from special ids."""
    prefix_len = PREFIX_BLOCKS * BLOCK_SIZE
    prefix = [3 + (tenant * 131 + i * 7) % (vocab - 4)
              for i in range(prefix_len)]
    tail = [3 + (nonce * 17 + i * 11) % (vocab - 4)
            for i in range(TAIL_TOKENS)]
    return prefix + tail


_MODEL = None


def _load_model():
    """One tiny model for every replica in the process (weights are
    identical across the fleet in production too)."""
    global _MODEL
    if _MODEL is None:
        import jax

        from kubeflow_tpu.models import llama as L

        cfg = L.LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        jax.block_until_ready(params)
        _MODEL = (params, cfg)
    return _MODEL


def _record_host() -> str:
    """``tpu`` or ``cpu`` next to ``provenance`` in every artifact: a
    smoke record from a CPU runner must never read like a chip number."""
    import jax

    return "tpu" if jax.default_backend() in ("tpu", "axon") else "cpu"


SLOTS = 2


def _pool_blocks(warm_chain_blocks: int) -> int:
    """ONE pool size for every engine in the run: jit shapes include the
    pool dims, so the shape warm-up only pays off if warm engine,
    measured replicas, and churn replicas all agree."""
    prompt_len = PREFIX_BLOCKS * BLOCK_SIZE + TAIL_TOKENS
    per_seq = -(-(prompt_len + DECODE_TOKENS) // BLOCK_SIZE) + 1
    return SLOTS * per_seq + warm_chain_blocks + 2


def _make_engine(warm_chain_blocks: int):
    from kubeflow_tpu.models.paged import PagedBatcher
    from kubeflow_tpu.models.serving import GenerationConfig

    params, cfg = _load_model()
    return PagedBatcher(
        params, cfg,
        gen=GenerationConfig(max_new_tokens=DECODE_TOKENS, eos_id=-1),
        slots=SLOTS, num_blocks=_pool_blocks(warm_chain_blocks),
        block_size=BLOCK_SIZE,
        prompt_bucket=PREFIX_BLOCKS * BLOCK_SIZE + 2 * BLOCK_SIZE,
        prefix_cache=True,
    )


def _warm_shapes(warm_chain_blocks: int) -> None:
    """Compile every prefill shape either arm can encounter BEFORE any
    arm is timed. The jit cache is process-wide, so whichever arm runs
    first would otherwise pay the compiles for both: a cache hit at m
    matched blocks prefills only the remaining suffix, and each m is a
    distinct padded shape. Partial evictions make every m in
    [0, PREFIX_BLOCKS] reachable. Dims match the replicas exactly —
    a compile at other pool dims warms nothing."""
    _, cfg = _load_model()
    pb = _make_engine(warm_chain_blocks)
    base = _tenant_prompt(0, 0, cfg.vocab_size)
    pb.submit(base, max_new_tokens=DECODE_TOKENS)  # m=0: full prefill
    pb.run()
    for m in range(1, PREFIX_BLOCKS + 1):
        shared = base[:m * BLOCK_SIZE]
        rest = [5 + m] * (len(base) - len(shared))
        pb.submit(shared + rest, max_new_tokens=DECODE_TOKENS)
        pb.run()


def _build_replicas(n: int, warm_chain_blocks: int):
    """n fresh InferenceServers over prefix-cached tiny PagedBatchers.
    Block pool: active slots' worst case + the configured warm-chain
    budget (+2 spare so back-to-back admissions do not immediately evict
    a warm chain) — sized so the fleet collectively caches every
    tenant's prefix but no single replica can cache all of them."""
    from kubeflow_tpu.models.server import InferenceServer

    _, cfg = _load_model()
    servers = []
    for _ in range(n):
        servers.append(InferenceServer(
            _make_engine(warm_chain_blocks), port=0, drain_s=2.0,
        ).start())
    return servers, cfg


def _stream_once(gw, prompt, tenant: str, timeout: float = 120.0):
    """One streaming completion through the gateway. Returns
    (ok, ttft_seconds, detail)."""
    conn = http.client.HTTPConnection(gw.host, gw.port, timeout=timeout)
    try:
        t0 = time.perf_counter()
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": prompt, "stream": True,
                        "max_tokens": DECODE_TOKENS,
                        "user": tenant}).encode(),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        if resp.status != 200:
            return False, 0.0, f"HTTP {resp.status}"
        ttft = None
        finished = False
        error = None
        while True:
            line = resp.fp.readline()
            if not line:
                break
            if not line.startswith(b"data:"):
                continue
            if line == b"data: [DONE]\n":
                finished = True
                break
            if ttft is None:
                ttft = time.perf_counter() - t0
            if b'"error"' in line:
                error = line.decode().strip()
        if not finished or error:
            return False, ttft or 0.0, error or "truncated stream"
        return True, ttft, ""
    except OSError as err:
        return False, 0.0, str(err)
    finally:
        conn.close()


def _drive_round(gw, tenants: int, nonce_base: int, vocab: int,
                 outcomes: list) -> None:
    """One round: every tenant issues one streaming request,
    concurrently (its own thread) — the gateway sees the interleaved
    multi-tenant arrival pattern routing decisions matter for."""
    threads = []
    for t in range(tenants):
        prompt = _tenant_prompt(t, nonce_base + t, vocab)

        def work(p=prompt, name=f"tenant-{t}"):
            outcomes.append(_stream_once(gw, p, name))

        th = threading.Thread(target=work, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join()


def _prefix_totals(servers) -> dict:
    hits = sum(s.engine.prefix_hits for s in servers)
    misses = sum(s.engine.prefix_misses for s in servers)
    evictions = sum(s.engine.prefix_evictions for s in servers)
    return {"hits": hits, "misses": misses, "evictions": evictions}


def _debug_json(gw, path: str) -> dict:
    """GET a gateway /debug endpoint — over HTTP on purpose, so the run
    exercises the JSON surface an operator (or the autoscaler) uses, not
    the in-process objects."""
    conn = http.client.HTTPConnection(gw.host, gw.port, timeout=10)
    try:
        conn.request("GET", path)
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def _build_telemetry():
    """Telemetry plane for one measured arm. Objectives are generous
    (the SLO gate asserts a HEALTHY run is silent, not that a tiny CPU
    model is fast); the window ring still spans the 30m slow window."""
    from kubeflow_tpu.observability.signals import (
        FleetTelemetry,
        SignalsConfig,
    )
    from kubeflow_tpu.observability.slo import default_objectives

    return FleetTelemetry(
        SignalsConfig(window_s=5.0, windows=360),
        objectives=default_objectives(
            ttft_p95_s=5.0, inter_token_p95_s=2.0, queue_wait_p95_s=5.0,
        ),
    )


def run_arm(affinity: str, *, replicas: int, tenants: int, rounds: int,
            warm_chain_blocks: int, warmup_rounds: int = 2) -> dict:
    from kubeflow_tpu.models.gateway import ServingGateway

    servers, cfg = _build_replicas(replicas, warm_chain_blocks)
    telemetry = _build_telemetry()
    gw = ServingGateway(
        [f"{s.host}:{s.port}" for s in servers], port=0,
        affinity=affinity, block_size=BLOCK_SIZE,
        health_interval_s=0.2, reroute_budget=2,
    ).start()
    try:
        # Warm-up: identical shapes (full-prefill AND cached-suffix
        # admissions both compile here), excluded from timing.
        for r in range(warmup_rounds):
            sink: list = []
            _drive_round(gw, tenants, 1_000_000 + r * tenants,
                         cfg.vocab_size, sink)
            bad = [d for ok, _, d in sink if not ok]
            if bad:
                raise RuntimeError(f"warm-up failures: {bad}")
        # Attach the telemetry plane only now: its series must cover
        # exactly the measured rounds, or cold warm-up TTFTs would skew
        # the p95 the agreement gate compares against the clients'.
        gw.telemetry = telemetry
        gw._tenant_buckets = telemetry.tenants
        before = _prefix_totals(servers)
        outcomes: list = []
        t0 = time.perf_counter()
        for r in range(rounds):
            _drive_round(gw, tenants, r * tenants, cfg.vocab_size,
                         outcomes)
        wall = time.perf_counter() - t0
        after = _prefix_totals(servers)
        gw.probe_once()  # final scrape → gateway-side aggregate view
        stats = gw.stats()
        signals = _debug_json(gw, "/debug/signals")
        slo = _debug_json(gw, "/debug/slo")
        failures = [d for ok, _, d in outcomes if not ok]
        ttfts = [ttft for ok, ttft, _ in outcomes if ok]
        completed = len(ttfts)
        hits = after["hits"] - before["hits"]
        misses = after["misses"] - before["misses"]
        # Telemetry-plane agreement: the gateway-measured TTFT p95 (the
        # autoscaler's input) vs the clients' own stopwatch, 15% with a
        # small absolute floor for loopback-scale jitter on tiny TTFTs.
        client_p95_ms = _p95_ms(ttfts) if ttfts else None
        tel_p95_s = (signals.get("fleet", {}).get("ttft_s") or {}).get("p95")
        tel_p95_ms = round(tel_p95_s * 1e3, 2) if tel_p95_s else None
        agrees = (
            client_p95_ms is not None and tel_p95_ms is not None
            and abs(tel_p95_ms - client_p95_ms)
            <= max(0.15 * client_p95_ms, 25.0)
        )
        breaches = sum(
            o["breaches_total"] for o in slo.get("objectives", {}).values()
        )
        return {
            "routing": affinity,
            "requests_completed": completed,
            "failures": failures,
            "requests_per_sec": round(completed / wall, 2),
            "p95_ttft_ms": _p95_ms(ttfts),
            "mean_ttft_ms": round(sum(ttfts) / len(ttfts) * 1e3, 2),
            "wall_s": round(wall, 3),
            "prefix_cache": {
                "hits": hits,
                "misses": misses,
                "evictions": after["evictions"] - before["evictions"],
                "hit_ratio": round(hits / (hits + misses), 4)
                if hits + misses else 0.0,
            },
            "gateway": {
                "reroutes": stats["reroutes"],
                "shed": stats["shed"],
                "failed": stats["failed"],
                "fleet_prefix_cache": stats.get("fleet_prefix_cache"),
            },
            # Telemetry plane vs client ground truth + the SLO verdict
            # (satellite: stamped into SERVE_*.json; smoke gates on it).
            "signals": {
                "ttft_p95_ms": tel_p95_ms,
                "client_p95_ttft_ms": client_p95_ms,
                "agrees_within_15pct": agrees,
                "requests_per_s": signals.get("fleet", {}).get(
                    "requests_per_s"),
            },
            "slo": {
                "breaching": slo.get("breaching", []),
                "breaches_total": breaches,
            },
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()


def run_churn(*, tenants: int, rounds: int,
              warm_chain_blocks: int) -> dict:
    """Elasticity on a live fleet: traffic flows while a replica JOINS
    (added to the ring mid-run) and another DRAINS (stop() flips its
    healthz; the probe routes around it while in-flight work finishes).
    Every request must complete — re-routed is fine, failed is not."""
    from kubeflow_tpu.models.gateway import ServingGateway

    servers, cfg = _build_replicas(2, warm_chain_blocks)
    gw = ServingGateway(
        [f"{s.host}:{s.port}" for s in servers], port=0,
        affinity="prefix", block_size=BLOCK_SIZE,
        health_interval_s=0.1, reroute_budget=2,
    ).start()
    joiner = None
    try:
        sink: list = []
        _drive_round(gw, tenants, 2_000_000, cfg.vocab_size, sink)  # warm
        outcomes: list = []
        events = []
        for r in range(rounds):
            if r == rounds // 3:
                (joiner,), _ = _build_replicas(1, warm_chain_blocks)
                gw.add_replica(f"{joiner.host}:{joiner.port}")
                events.append(f"round {r}: replica joined")
            if r == 2 * rounds // 3:
                threading.Thread(target=servers[0].stop,
                                 daemon=True).start()
                events.append(f"round {r}: replica draining")
            _drive_round(gw, tenants, 3_000_000 + r * tenants,
                         cfg.vocab_size, outcomes)
        deadline = time.monotonic() + 30
        want = {f"{s.host}:{s.port}" for s in (servers[1], joiner)}
        while gw.ring_nodes() != frozenset(want) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        stats = gw.stats()
        failures = [d for ok, _, d in outcomes if not ok]
        return {
            "requests": len(outcomes),
            "failures": failures,
            "events": events,
            "reroutes": stats["reroutes"],
            "gateway_failed": stats["failed"],
            "ring_converged": gw.ring_nodes() == frozenset(want),
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()
        if joiner is not None:
            joiner.stop()


def _verify_trace_export(min_chains: int):
    """When ``KUBEFLOW_TPU_TRACE_EXPORT`` is set, the run doubles as the
    tracing executability gate: the JSONL export must contain a complete
    gateway→engine span chain (gateway.request → gateway.route →
    server.request → queue_wait → prefill, one shared trace id) for at
    least every completed request in the measured arms. Returns a small
    summary dict, or None when export is off."""
    from kubeflow_tpu.webhook.tpu_env import KUBEFLOW_TPU_TRACE_EXPORT

    path = os.environ.get(KUBEFLOW_TPU_TRACE_EXPORT, "")
    if not path:
        return None
    chain = {"gateway.request", "gateway.route", "server.request",
             "queue_wait", "prefill"}
    by_trace: dict = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            span = json.loads(line)
            by_trace.setdefault(span["trace_id"], set()).add(span["name"])
    chains = sum(1 for names in by_trace.values() if chain <= names)
    if chains < min_chains:
        raise SystemExit(
            f"trace export {path}: only {chains} complete gateway→engine "
            f"span chains for {min_chains} completed requests"
        )
    print(f"# trace export: {chains} complete gateway→engine chains "
          f"across {len(by_trace)} traces ({path})", file=sys.stderr)
    return {"complete_chains": chains, "traces": len(by_trace)}


# -- disaggregated prefill/decode arm (--disagg) ------------------------

DISAGG_LONG_BLOCKS = 12    # storm prompt length, in full KV blocks
DISAGG_SHORT_TOKENS = 20   # one full block + a short tail
DISAGG_DECODE_TOKENS = 10  # 9 inter-token gaps per short request
DISAGG_SLOTS = 4


def _disagg_prompt(nonce: int, length: int, vocab: int) -> list:
    """Unique prompt per request (arithmetic in the nonce, no RNG): the
    storm measures PREFILL interference with decode, so nothing may
    prefix-hit and skip its prefill."""
    return [3 + (nonce * 131 + i * 7) % (vocab - 4) for i in range(length)]


def _make_disagg_engine():
    from kubeflow_tpu.models.paged import PagedBatcher, pool_blocks_from_hbm
    from kubeflow_tpu.models.serving import GenerationConfig

    params, cfg = _load_model()
    bucket = (DISAGG_LONG_BLOCKS + 2) * BLOCK_SIZE
    per_seq = -(-(bucket + DISAGG_DECODE_TOKENS) // BLOCK_SIZE) + 1
    floor = DISAGG_SLOTS * per_seq + 2
    # Pools size themselves from the device's real HBM budget
    # (memory_stats) on TPU; on CPU (no memory_stats) the fallback IS
    # the computed worst-case constant, and the max() keeps a tiny HBM
    # answer from under-sizing below what the slots can demand.
    blocks = max(pool_blocks_from_hbm(
        cfg, BLOCK_SIZE, fraction=0.3, fallback=floor), floor)
    return PagedBatcher(
        params, cfg,
        gen=GenerationConfig(max_new_tokens=DISAGG_DECODE_TOKENS,
                             eos_id=-1),
        slots=DISAGG_SLOTS, num_blocks=blocks, block_size=BLOCK_SIZE,
        prompt_bucket=bucket, prefix_cache=True,
    )


def _build_disagg_fleet(mode: str):
    """mode="disagg": 1 prefill + 2 decode replicas behind a tier-aware
    gateway; mode="fused": the control — 3 fused replicas, same engines
    and total capacity, only the tier split differs."""
    from kubeflow_tpu.models.gateway import ServingGateway
    from kubeflow_tpu.models.server import InferenceServer

    _, cfg = _load_model()
    roles = (["prefill", "decode", "decode"] if mode == "disagg"
             else ["fused"] * 3)
    servers = [
        InferenceServer(_make_disagg_engine(), port=0, drain_s=2.0,
                        tier_role=role).start()
        for role in roles
    ]
    tier_roles = {f"{s.host}:{s.port}": role
                  for s, role in zip(servers, roles) if role != "fused"}
    gw = ServingGateway(
        [f"{s.host}:{s.port}" for s in servers], port=0,
        affinity="prefix", block_size=BLOCK_SIZE, health_interval_s=0.2,
        reroute_budget=2,
        tier_mode="disagg" if mode == "disagg" else "fused",
        tier_roles=tier_roles,
    ).start()
    return gw, servers, cfg


def _stream_gaps(gw, prompt, tenant: str, timeout: float = 120.0):
    """One streaming completion; returns (ok, [inter-token gaps in
    seconds], detail). The gaps — wall-clock between consecutive SSE
    data lines at the client — are the decode-interference signal the
    disagg arm gates on."""
    conn = http.client.HTTPConnection(gw.host, gw.port, timeout=timeout)
    try:
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": prompt, "stream": True,
                        "max_tokens": DISAGG_DECODE_TOKENS,
                        "user": tenant}).encode(),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        if resp.status != 200:
            return False, [], f"HTTP {resp.status}"
        gaps: list = []
        last = None
        finished = False
        error = None
        while True:
            line = resp.fp.readline()
            if not line:
                break
            if not line.startswith(b"data:"):
                continue
            if line == b"data: [DONE]\n":
                finished = True
                break
            if b'"error"' in line:
                error = line.decode().strip()
                continue
            now = time.perf_counter()
            if last is not None:
                gaps.append(now - last)
            last = now
        if not finished or error:
            return False, gaps, error or "truncated stream"
        return True, gaps, ""
    except OSError as err:
        return False, [], str(err)
    finally:
        conn.close()


def _drive_disagg_round(gw, vocab: int, nonce_base: int, per_round: int,
                        long_every: int, outcomes: list) -> None:
    """One concurrent round. long_every=0 → all-short (the quiet
    baseline); long_every=4 → the 1-in-4 long-prompt storm."""
    threads = []
    for i in range(per_round):
        is_long = bool(long_every) and i % long_every == 0
        length = (DISAGG_LONG_BLOCKS * BLOCK_SIZE + 3 if is_long
                  else DISAGG_SHORT_TOKENS)
        prompt = _disagg_prompt(nonce_base + i, length, vocab)

        def work(p=prompt, lng=is_long, name=f"tenant-{i % 4}"):
            ok, gaps, detail = _stream_gaps(gw, p, name)
            outcomes.append((lng, ok, gaps, detail))

        th = threading.Thread(target=work, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join()


def run_disagg_arm(mode: str, *, rounds: int, per_round: int) -> dict:
    gw, servers, cfg = _build_disagg_fleet(mode)
    telemetry = _build_telemetry()
    try:
        # Warm-up: one storm-shaped round compiles EVERY shape either
        # phase can hit (short/long prefill, KV export gathers, import
        # writes at both block counts) before anything is timed.
        sink: list = []
        _drive_disagg_round(gw, cfg.vocab_size, 5_000_000, per_round, 4,
                            sink)
        bad = [d for _, ok, _, d in sink if not ok]
        if bad:
            raise RuntimeError(f"{mode} warm-up failures: {bad}")
        gw.telemetry = telemetry
        gw._tenant_buckets = telemetry.tenants
        quiet: list = []
        for r in range(rounds):
            _drive_disagg_round(gw, cfg.vocab_size, r * per_round,
                                per_round, 0, quiet)
        storm: list = []
        for r in range(rounds):
            _drive_disagg_round(gw, cfg.vocab_size,
                                1_000_000 + r * per_round, per_round, 4,
                                storm)
        gw.probe_once()
        stats = gw.stats()
        signals = _debug_json(gw, "/debug/signals")
        slo = _debug_json(gw, "/debug/slo")
        failures = [d for _, ok, _, d in quiet + storm if not ok]
        quiet_gaps = [g for _, ok, gaps, _ in quiet if ok for g in gaps]
        # The gate reads SHORT requests only: a long request's own gaps
        # say nothing about cross-request interference.
        storm_gaps = [g for lng, ok, gaps, _ in storm
                      if ok and not lng for g in gaps]
        quiet_p95 = _p95_ms(quiet_gaps) if quiet_gaps else 0.0
        storm_p95 = _p95_ms(storm_gaps) if storm_gaps else 0.0
        breaches = sum(o["breaches_total"]
                       for o in slo.get("objectives", {}).values())
        return {
            "mode": mode,
            "requests_completed": sum(
                1 for _, ok, _, _ in quiet + storm if ok),
            "failures": failures,
            "quiet_inter_token_p95_ms": quiet_p95,
            "storm_inter_token_p95_ms": storm_p95,
            "storm_over_quiet": round(storm_p95 / max(quiet_p95, 1e-9), 3),
            "kv_transfers": stats["kv_transfers"],
            "kv_transfer_failures": stats["kv_transfer_failures"],
            "kv_transfer_bytes": stats["kv_transfer_bytes"],
            "kv_transfer_latency_s": stats["kv_transfer_latency_s"],
            "signals_kv_transfer_s": (signals.get("fleet") or {}).get(
                "kv_transfer_s"),
            "slo": {
                "breaching": slo.get("breaching", []),
                "breaches_total": breaches,
            },
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()


def main_disagg(args) -> int:
    """--disagg: the tier-split experiment. The disagg fleet's decode
    tier must stay flat through the long-prompt storm (p95 inter-token
    ≤ 1.1× its own quiet baseline, small absolute floor for loopback
    jitter) while the same-capacity fused fleet degrades — plus the PR
    11 SLO gate (zero breaches) and zero failed requests on both arms."""
    global DISAGG_LONG_BLOCKS, DISAGG_DECODE_TOKENS
    rounds, per_round = 3, 8
    if args.smoke:
        DISAGG_LONG_BLOCKS, DISAGG_DECODE_TOKENS = 4, 6
        rounds, per_round = 1, 4
    print("# disagg arm: 1 prefill + 2 decode replicas, 1-in-4 "
          "long-prompt storm ...", file=sys.stderr)
    disagg = run_disagg_arm("disagg", rounds=rounds, per_round=per_round)
    print("# fused control arm (same engines, no tier split) ...",
          file=sys.stderr)
    fused = run_disagg_arm("fused", rounds=rounds, per_round=per_round)

    record = {
        "scenario": (
            f"1-in-4 long-prompt storm ({DISAGG_LONG_BLOCKS} blocks) over "
            "a 1-prefill + 2-decode tier split with paged-KV handoff vs "
            "the same 3 engines fused"
        ),
        "model": "tiny",
        "block_size": BLOCK_SIZE,
        "long_blocks": DISAGG_LONG_BLOCKS,
        "decode_tokens": DISAGG_DECODE_TOKENS,
        "rounds": rounds,
        "per_round": per_round,
        "provenance": "smoke" if args.smoke else "live",
        "host": _record_host(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "disagg": disagg,
        "fused": fused,
    }
    print(json.dumps({
        "disagg_quiet_p95_ms": disagg["quiet_inter_token_p95_ms"],
        "disagg_storm_p95_ms": disagg["storm_inter_token_p95_ms"],
        "disagg_storm_over_quiet": disagg["storm_over_quiet"],
        "fused_storm_over_quiet": fused["storm_over_quiet"],
        "kv_transfers": disagg["kv_transfers"],
        "kv_transfer_failures": disagg["kv_transfer_failures"],
        "slo_breaches": (disagg["slo"]["breaches_total"]
                         + fused["slo"]["breaches_total"]),
    }))
    clean = (
        not disagg["failures"] and not fused["failures"]
        and disagg["kv_transfers"] > 0
        and disagg["kv_transfer_failures"] == 0
        and disagg["slo"]["breaches_total"] == 0
        and fused["slo"]["breaches_total"] == 0
    )
    if not clean:
        print("# disagg gate FAILED: " + json.dumps({
            "disagg_failures": disagg["failures"],
            "fused_failures": fused["failures"],
            "kv": {k: disagg[k] for k in
                   ("kv_transfers", "kv_transfer_failures")},
            "slo": {"disagg": disagg["slo"], "fused": fused["slo"]},
        }), file=sys.stderr)
    if args.smoke:
        print("# --smoke: artifact write and win gate skipped",
              file=sys.stderr)
        return 0 if clean else 1
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(f"# wrote {args.out}", file=sys.stderr)
    flat = (
        disagg["storm_inter_token_p95_ms"]
        <= max(1.1 * disagg["quiet_inter_token_p95_ms"],
               disagg["quiet_inter_token_p95_ms"] + 10.0)
    )
    degrades = fused["storm_over_quiet"] > 1.1
    win = clean and flat and degrades
    if not win:
        print("# win gate: " + json.dumps({
            "decode_tier_flat": flat, "fused_degrades": degrades,
        }), file=sys.stderr)
    return 0 if win else 1


# -- HBM-economy eviction-storm arm (--evict-storm) ---------------------

EVICT_PREFIX_BLOCKS = 6    # each tenant's chain, in full KV blocks
EVICT_TAIL_TOKENS = 7      # unique per-request suffix
EVICT_DECODE_TOKENS = 8
EVICT_SLOTS = 2
EVICT_BUDGET_CHAINS = 4    # warm chains the bf16 baseline pool can hold


def _evict_prompt(tenant: int, nonce: int, vocab: int) -> list:
    """Per-TENANT chain (shared across the tenant's returns) + a unique
    tail, deterministic like _tenant_prompt but sized by the evict-storm
    globals."""
    prefix = [3 + (tenant * 131 + i * 7) % (vocab - 4)
              for i in range(EVICT_PREFIX_BLOCKS * BLOCK_SIZE)]
    tail = [3 + (nonce * 17 + i * 11) % (vocab - 4)
            for i in range(EVICT_TAIL_TOKENS)]
    return prefix + tail


def _evict_block_bytes(kv_bits: int) -> int:
    """Measured (not derived) per-block HBM bytes for the pool format:
    sum the probe pool's leaf bytes so the bf16 and int8 arms are sized
    from the SAME byte budget the engine actually allocates."""
    from kubeflow_tpu.models.paged import PagedBatcher

    params, cfg = _load_model()
    probe = PagedBatcher(params, cfg, slots=1, num_blocks=2,
                         block_size=BLOCK_SIZE, prompt_bucket=BLOCK_SIZE,
                         kv_bits=kv_bits)
    return sum(leaf.nbytes for leaf in probe.pool.values()) // 2


def _make_evict_engine(kv_bits: int, num_blocks: int, swap_bytes: int):
    from kubeflow_tpu.models.paged import PagedBatcher
    from kubeflow_tpu.models.serving import GenerationConfig

    params, cfg = _load_model()
    prompt_len = EVICT_PREFIX_BLOCKS * BLOCK_SIZE + EVICT_TAIL_TOKENS
    return PagedBatcher(
        params, cfg,
        gen=GenerationConfig(max_new_tokens=EVICT_DECODE_TOKENS, eos_id=-1),
        slots=EVICT_SLOTS, num_blocks=num_blocks, block_size=BLOCK_SIZE,
        prompt_bucket=-(-prompt_len // BLOCK_SIZE) * BLOCK_SIZE,
        prefix_cache=True, kv_bits=kv_bits, swap_bytes=swap_bytes,
        # Block-wide admission pieces: ONE prefill shape regardless of
        # how many chain blocks hit, so TTFT tracks blocks actually
        # prefilled instead of which padded bucket they landed in.
        admit_chunk=BLOCK_SIZE,
    )


def _evict_pool_floor() -> int:
    prompt_len = EVICT_PREFIX_BLOCKS * BLOCK_SIZE + EVICT_TAIL_TOKENS
    per_seq = -(-(prompt_len + EVICT_DECODE_TOKENS) // BLOCK_SIZE) + 1
    return EVICT_SLOTS * per_seq + 2


def _stream_evict(host, port, prompt, tenant: str, timeout: float = 120.0):
    """One streaming completion straight at a replica (no gateway: the
    storm is a single-chip HBM story). Returns (ok, ttft_s, [inter-token
    gaps s], detail) — TTFT carries the re-prefill vs swap-restore
    signal, the gaps isolate decode speed from admission work."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        t0 = time.perf_counter()
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": prompt, "stream": True,
                        "max_tokens": EVICT_DECODE_TOKENS,
                        "user": tenant}).encode(),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        if resp.status != 200:
            return False, 0.0, [], f"HTTP {resp.status}"
        ttft = None
        gaps: list = []
        last = None
        finished = False
        error = None
        while True:
            line = resp.fp.readline()
            if not line:
                break
            if not line.startswith(b"data:"):
                continue
            if line == b"data: [DONE]\n":
                finished = True
                break
            if b'"error"' in line:
                error = line.decode().strip()
                continue
            now = time.perf_counter()
            if ttft is None:
                ttft = now - t0
            if last is not None:
                gaps.append(now - last)
            last = now
        if not finished or error:
            return False, ttft or 0.0, gaps, error or "truncated stream"
        return True, ttft, gaps, ""
    except OSError as err:
        return False, 0.0, [], str(err)
    finally:
        conn.close()


def _drive_evict_round(server, tenants: int, nonce_base: int, vocab: int,
                       outcomes: list) -> None:
    """Every tenant returns once, concurrently — with a pool that holds
    only EVICT_BUDGET_CHAINS warm chains, each admission evicts someone
    else's chain: the storm."""
    threads = []
    for t in range(tenants):
        prompt = _evict_prompt(t, nonce_base + t, vocab)

        def work(p=prompt, name=f"tenant-{t}"):
            outcomes.append(_stream_evict(server.host, server.port, p,
                                          name))

        th = threading.Thread(target=work, daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join()


def run_evict_arm(label: str, kv_bits: int, swap: bool, *, tenants: int,
                  rounds: int, hbm_bytes: int) -> dict:
    """One arm of the storm on one replica sized from ``hbm_bytes``:
    the baseline (bf16, no swap) loses every demoted chain to a full
    re-prefill; the treatment (int8 + host swap) fits ~2x the chains on
    chip and restores the rest from host RAM."""
    from kubeflow_tpu.models.gateway import prompt_chain_keys
    from kubeflow_tpu.models.server import InferenceServer

    _, cfg = _load_model()
    per_block = _evict_block_bytes(kv_bits)
    num_blocks = max(_evict_pool_floor(), hbm_bytes // per_block)
    chain_bytes = EVICT_PREFIX_BLOCKS * per_block
    swap_bytes = 2 * tenants * chain_bytes if swap else 0
    engine = _make_evict_engine(kv_bits, num_blocks, swap_bytes)
    server = InferenceServer(engine, port=0, drain_s=2.0).start()
    try:
        sink: list = []
        _drive_evict_round(server, tenants, 4_000_000, cfg.vocab_size,
                           sink)  # warm-up: compiles + first prefills
        bad = [d for ok, _, _, d in sink if not ok]
        if bad:
            raise RuntimeError(f"{label} warm-up failures: {bad}")
        before_hits = engine.prefix_hits
        before_misses = engine.prefix_misses
        outcomes: list = []
        t0 = time.perf_counter()
        for r in range(rounds):
            _drive_evict_round(server, tenants, r * tenants,
                               cfg.vocab_size, outcomes)
        wall = time.perf_counter() - t0
        failures = [d for ok, _, _, d in outcomes if not ok]
        ttfts = [ttft for ok, ttft, _, _ in outcomes if ok]
        gaps = [g for ok, _, gs, _ in outcomes if ok for g in gs]
        # Concurrent resident sessions: tenants whose FULL chain is
        # device-resident after the storm — the pool-capacity number the
        # int8 halving is supposed to double.
        with server._lock:
            resident = 0
            for t in range(tenants):
                keys = prompt_chain_keys(
                    _evict_prompt(t, 0, cfg.vocab_size)
                    [:EVICT_PREFIX_BLOCKS * BLOCK_SIZE], BLOCK_SIZE)
                if all(k in engine._prefix_entries for k in keys):
                    resident += 1
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=10)
        try:
            conn.request("GET", "/stats")
            stats = json.loads(conn.getresponse().read())
        finally:
            conn.close()
        hits = engine.prefix_hits - before_hits
        misses = engine.prefix_misses - before_misses
        return {
            "arm": label,
            "kv_bits": kv_bits,
            "swap_enabled": swap,
            "num_blocks": num_blocks,
            "pool_bytes": num_blocks * per_block,
            "requests_completed": len(ttfts),
            "failures": failures,
            "resident_sessions": resident,
            "p95_ttft_ms": _p95_ms(ttfts) if ttfts else None,
            "mean_ttft_ms": round(sum(ttfts) / len(ttfts) * 1e3, 2)
            if ttfts else None,
            # Inter-token gaps isolate decode speed from admission work;
            # the 5% gate compares the arms on THIS number.
            "decode_tokens_per_sec": round(len(gaps) / sum(gaps), 2)
            if gaps else None,
            "wall_s": round(wall, 3),
            "prefix_cache": {
                "hits": hits,
                "misses": misses,
                "hit_ratio": round(hits / (hits + misses), 4)
                if hits + misses else 0.0,
            },
            "kv_swap": stats.get("kv_swap"),
            "kv_pool": stats.get("kv_pool"),
        }
    finally:
        server.stop()


def main_evict(args) -> int:
    """--evict-storm: oversubscribed tenants cycling through one
    replica's pool. Baseline bf16/no-swap re-prefills every returning
    chain; the int8+swap treatment must hold >= 2x the resident sessions
    on the same byte budget, decode within 5%, and beat the baseline's
    p95 TTFT via swap restores."""
    global EVICT_PREFIX_BLOCKS, EVICT_DECODE_TOKENS, EVICT_BUDGET_CHAINS
    tenants, rounds = args.tenants * 2, args.rounds
    if args.smoke:
        # Small model/short chains, but still OVERSUBSCRIBED — for BOTH
        # arms: 12 tenants x 3 blocks must exceed even the int8 pool
        # (~2x the baseline's blocks), or the treatment never demotes
        # and the swap path goes unexercised.
        EVICT_PREFIX_BLOCKS, EVICT_DECODE_TOKENS = 3, 4
        EVICT_BUDGET_CHAINS = 1
        tenants, rounds = 12, 2
    # ONE byte budget for both arms: what the bf16 pool needs to keep
    # EVICT_BUDGET_CHAINS chains warm beyond its active slots. The int8
    # arm spends the same bytes on ~2x the blocks.
    hbm_bytes = _evict_block_bytes(0) * (
        _evict_pool_floor() + EVICT_BUDGET_CHAINS * EVICT_PREFIX_BLOCKS
    )
    print(f"# evict-storm baseline: bf16, no swap ({tenants} tenants x "
          f"{rounds} rounds, {hbm_bytes} pool bytes) ...", file=sys.stderr)
    baseline = run_evict_arm("evict_reprefill", 0, False, tenants=tenants,
                             rounds=rounds, hbm_bytes=hbm_bytes)
    print("# evict-storm treatment: int8 KV + host-RAM swap ...",
          file=sys.stderr)
    treatment = run_evict_arm("int8_swap", 8, True, tenants=tenants,
                              rounds=rounds, hbm_bytes=hbm_bytes)

    resident_ratio = round(
        treatment["resident_sessions"]
        / max(baseline["resident_sessions"], 1), 3)
    decode_ratio = round(
        (treatment["decode_tokens_per_sec"] or 0.0)
        / max(baseline["decode_tokens_per_sec"] or 1e-9, 1e-9), 3)
    record = {
        "scenario": (
            f"{tenants} tenants with {EVICT_PREFIX_BLOCKS}-block chains "
            "cycling through one replica whose pool holds "
            f"{EVICT_BUDGET_CHAINS} warm bf16 chains: evict+re-prefill "
            "vs int8 KV + host-RAM swap on the same byte budget"
        ),
        "model": "tiny",
        "block_size": BLOCK_SIZE,
        "prefix_blocks": EVICT_PREFIX_BLOCKS,
        "decode_tokens": EVICT_DECODE_TOKENS,
        "tenants": tenants,
        "rounds": rounds,
        "pool_byte_budget": hbm_bytes,
        "provenance": "smoke" if args.smoke else "live",
        "host": _record_host(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "baseline": baseline,
        "treatment": treatment,
        "resident_sessions_ratio": resident_ratio,
        "decode_tokens_per_sec_ratio": decode_ratio,
    }
    print(json.dumps({
        "baseline_resident_sessions": baseline["resident_sessions"],
        "treatment_resident_sessions": treatment["resident_sessions"],
        "resident_sessions_ratio": resident_ratio,
        "baseline_p95_ttft_ms": baseline["p95_ttft_ms"],
        "treatment_p95_ttft_ms": treatment["p95_ttft_ms"],
        "decode_tokens_per_sec_ratio": decode_ratio,
        "swap_out": (treatment["kv_swap"] or {}).get("swap_out"),
        "swap_in": (treatment["kv_swap"] or {}).get("swap_in"),
    }))
    swap_stats = treatment["kv_swap"] or {}
    clean = (
        not baseline["failures"] and not treatment["failures"]
        and swap_stats.get("swap_out", 0) > 0
        and swap_stats.get("swap_in", 0) > 0
    )
    if not clean:
        print("# evict-storm gate FAILED: " + json.dumps({
            "baseline_failures": baseline["failures"],
            "treatment_failures": treatment["failures"],
            "kv_swap": swap_stats,
        }), file=sys.stderr)
    if args.smoke:
        print("# --smoke: artifact write and win gate skipped",
              file=sys.stderr)
        return 0 if clean else 1
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(f"# wrote {args.out}", file=sys.stderr)
    win = (
        clean
        and resident_ratio >= 2.0
        and decode_ratio >= 0.95
        and treatment["p95_ttft_ms"] < baseline["p95_ttft_ms"]
    )
    if not win:
        print("# win gate: " + json.dumps({
            "resident_ratio_ge_2x": resident_ratio >= 2.0,
            "decode_within_5pct": decode_ratio >= 0.95,
            "swap_beats_reprefill_ttft":
                treatment["p95_ttft_ms"] < baseline["p95_ttft_ms"],
        }), file=sys.stderr)
    return 0 if win else 1


# ---------------------------------------------------------------------------
# --spec / --multilora (r10): speculation as a ragged scheduling mode +
# multi-LoRA serving with (prefix, adapter) affinity routing.
# ---------------------------------------------------------------------------

SPEC_SLOTS = 2             # decode slots; each contributes 1+k verify rows
SPEC_K = 7                 # draft length (verify span = 8 rows/slot)
SPEC_REQUESTS = 6
SPEC_DECODE_TOKENS = 32
SPEC_DAMP = 0.05           # per-layer residual damping (see _spec_models)

ML_REPLICAS = 4
ML_ADAPTERS = 64
ML_CACHE_SLOTS = 16        # hot adapters resident per replica
ML_LOAD_S = 0.02           # simulated adapter-load stall on a cache miss
ML_ROUNDS = 3
ML_PREFIX_TOKENS = 16      # ONE system prompt shared by every adapter
ML_TAIL_TOKENS = 5
ML_DECODE_TOKENS = 6
ML_CONCURRENCY = 16


def _spec_models():
    """Target in a draft-friendly regime: damp the per-layer residual
    contributions so the embed/head pair (SHARED with the truncated
    draft) dominates the argmax. A 1-layer draft then agrees with the
    full target often — the high-acceptance regime a trained draft
    earns — while every miss still exercises the real verify-reject-
    rollback machinery, and the token-exactness gate is checked against
    the plain scheduler either way."""
    import jax.tree_util as jtu

    from kubeflow_tpu.models.speculative import truncated_draft

    params, cfg = _load_model()
    params = dict(params, layers=jtu.tree_map(
        lambda x: x * SPEC_DAMP, params["layers"]))
    dparams, dcfg = truncated_draft(params, cfg, 1)
    return params, cfg, dparams, dcfg


def _bench_decode(engine, prompts):
    """Warm-up pass (compiles every dispatch shape), then one timed
    pass of the same prompts: (sorted streams, tokens/sec, wall_s)."""
    for p in prompts:
        engine.submit(p)
    engine.run()
    t0 = time.perf_counter()
    for p in prompts:
        engine.submit(p)
    out = engine.run()
    wall = time.perf_counter() - t0
    toks = sum(len(v) for v in out.values())
    return (sorted(tuple(v) for v in out.values()),
            round(toks / wall, 2), round(wall, 3))


def run_spec_arm() -> dict:
    """Engine-level decode bench: plain ragged PagedBatcher vs the SAME
    engine in speculative scheduling mode (each slot contributing
    1+k_spec verify rows to the fused dispatch). The streams must be
    token-identical; the speedup is rounds saved by acceptance."""
    from kubeflow_tpu.models.paged import PagedBatcher
    from kubeflow_tpu.models.serving import GenerationConfig
    from kubeflow_tpu.models.speculative import SpeculativePagedBatcher

    params, cfg, dparams, dcfg = _spec_models()
    gen = GenerationConfig(max_new_tokens=SPEC_DECODE_TOKENS, eos_id=-1)
    prompts = [[3 + (s * 37 + i) % (cfg.vocab_size - 4) for i in range(6)]
               for s in range(SPEC_REQUESTS)]
    kw = dict(gen=gen, slots=SPEC_SLOTS, num_blocks=64, block_size=8,
              prompt_bucket=16)
    plain = PagedBatcher(params, cfg, attn_kernel=False, ragged=True,
                         token_budget=4 * SPEC_SLOTS, **kw)
    plain_out, plain_tps, plain_wall = _bench_decode(plain, prompts)
    spec = SpeculativePagedBatcher(
        params, cfg, dparams, dcfg, k_spec=SPEC_K, ragged=True,
        token_budget=SPEC_SLOTS * (SPEC_K + 1), **kw)
    spec_out, spec_tps, spec_wall = _bench_decode(spec, prompts)
    return {
        "requests": SPEC_REQUESTS,
        "slots": SPEC_SLOTS,
        "k_spec": SPEC_K,
        "decode_tokens": SPEC_DECODE_TOKENS,
        "token_exact": plain_out == spec_out,
        "plain_tokens_per_sec": plain_tps,
        "spec_tokens_per_sec": spec_tps,
        "speedup": round(spec_tps / max(plain_tps, 1e-9), 3),
        "acceptance_rate": round(spec.acceptance_rate, 4),
        "verify_rounds": spec.rounds,
        "plain_wall_s": plain_wall,
        "spec_wall_s": spec_wall,
    }


def _ml_prompt(adapter_id: int, nonce: int, vocab: int) -> list:
    """ONE system prompt shared across every adapter (the worst case
    for an adapter-oblivious prefix router: all 64 adapters' traffic
    hashes to a single replica) + a unique per-request tail."""
    prefix = [3 + (i * 7) % (vocab - 4) for i in range(ML_PREFIX_TOKENS)]
    tail = [3 + (adapter_id * 131 + nonce * 17 + i * 11) % (vocab - 4)
            for i in range(ML_TAIL_TOKENS)]
    return prefix + tail


def _ml_build_fleet(adapter_affinity: bool):
    from kubeflow_tpu.models.gateway import ServingGateway
    from kubeflow_tpu.models.lora import LoraConfig, init_lora_params
    from kubeflow_tpu.models.multilora import (
        MultiLoraPagedBatcher,
        stack_adapters,
    )
    from kubeflow_tpu.models.server import InferenceServer
    from kubeflow_tpu.models.serving import GenerationConfig

    import jax

    params, cfg = _load_model()
    lcfg = LoraConfig(rank=2, targets=("wq", "wv"))
    adapters = [init_lora_params(cfg, lcfg, jax.random.PRNGKey(seed))
                for seed in range(ML_ADAPTERS)]
    stacked = stack_adapters(adapters, cfg, lcfg)
    names = [f"ad{i}" for i in range(ML_ADAPTERS)]
    servers = []
    for _ in range(ML_REPLICAS):
        engine = MultiLoraPagedBatcher(
            params, cfg, stacked, lcfg, adapter_names=names,
            gen=GenerationConfig(max_new_tokens=ML_DECODE_TOKENS,
                                 eos_id=-1),
            slots=4, num_blocks=64, block_size=8, prompt_bucket=32,
            attn_kernel=False, ragged=True, token_budget=16,
            lora_cache_slots=ML_CACHE_SLOTS, lora_load_s=ML_LOAD_S,
        )
        servers.append(InferenceServer(
            engine, port=0, drain_s=2.0,
            max_queue_depth=4 * ML_ADAPTERS,  # queue, don't shed: the
            # oblivious arm funnels the whole fleet's load to one
            # replica and the p95 must show that, not 429s
        ).start())
    gw = ServingGateway(
        [f"{s.host}:{s.port}" for s in servers], port=0, block_size=8,
        health_interval_s=0.2, upstream_timeout_s=600.0,
        adapter_affinity=adapter_affinity,
    ).start()
    return gw, servers, cfg


def _ml_stream(gw, prompt, model, timeout: float = 600.0):
    """One streaming completion with an adapter selection. Returns
    (ok, ttft_seconds, detail)."""
    body = {"prompt": prompt, "stream": True,
            "max_tokens": ML_DECODE_TOKENS}
    if model is not None:
        body["model"] = model
    conn = http.client.HTTPConnection(gw.host, gw.port, timeout=timeout)
    try:
        t0 = time.perf_counter()
        conn.request("POST", "/v1/completions", json.dumps(body).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            return False, 0.0, f"HTTP {resp.status}"
        ttft = None
        finished = False
        error = None
        while True:
            line = resp.fp.readline()
            if not line:
                break
            if not line.startswith(b"data:"):
                continue
            if line == b"data: [DONE]\n":
                finished = True
                break
            if ttft is None:
                ttft = time.perf_counter() - t0
            if b'"error"' in line:
                error = line.decode().strip()
        if not finished or error:
            return False, ttft or 0.0, error or "truncated stream"
        return True, ttft, ""
    except OSError as err:
        return False, 0.0, str(err)
    finally:
        conn.close()


def run_multilora_arm(label: str, adapter_affinity: bool) -> dict:
    """One routing arm over a fresh fleet: ML_ADAPTERS adapters sharing
    ONE system prompt over ML_REPLICAS replicas whose hot-adapter cache
    holds ML_CACHE_SLOTS. (prefix, adapter) affinity spreads the
    adapters so each replica's share fits its cache; the oblivious
    router sends everything to the prefix's one ring owner, which then
    thrashes adapter loads forever (and serves the fleet's whole load
    alone)."""
    gw, servers, cfg = _ml_build_fleet(adapter_affinity)
    try:
        # Warm-up straight at each replica (no gateway, base model):
        # both arms compile the same shapes regardless of routing.
        for s in servers:
            class _GW:  # _ml_stream wants .host/.port
                host, port = s.host, s.port
            ok, _, detail = _ml_stream(_GW, _ml_prompt(0, 10**6,
                                                       cfg.vocab_size),
                                       None)
            if not ok:
                raise RuntimeError(f"{label} warm-up failure: {detail}")
        outcomes: list = []
        sem = threading.Semaphore(ML_CONCURRENCY)
        t0 = time.perf_counter()
        for rnd in range(ML_ROUNDS):
            threads = []
            for a in range(ML_ADAPTERS):
                prompt = _ml_prompt(a, rnd, cfg.vocab_size)

                def work(p=prompt, m=f"ad{a}"):
                    with sem:
                        got = _ml_stream(gw, p, m)
                        if not got[0] and "Errno" in got[2]:
                            # Transient loopback reset under the
                            # accept burst: one client-side retry,
                            # like any production client.
                            got = _ml_stream(gw, p, m)
                        outcomes.append(got)

                th = threading.Thread(target=work, daemon=True)
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
        wall = time.perf_counter() - t0
        failures = [d for ok, _, d in outcomes if not ok]
        ttfts = [t for ok, t, _ in outcomes if ok]
        cache = {"hits": 0, "misses": 0, "evictions": 0}
        served_by = []  # adapter-cache touches per replica: the spread
        for s in servers:
            st = s.engine.lora_cache_stats()
            for k in cache:
                cache[k] += st[k]
            served_by.append(st["hits"] + st["misses"])
        total = cache["hits"] + cache["misses"]
        return {
            "arm": label,
            "adapter_affinity": adapter_affinity,
            "replicas": ML_REPLICAS,
            "adapters": ML_ADAPTERS,
            "cache_slots": ML_CACHE_SLOTS,
            "rounds": ML_ROUNDS,
            "requests_completed": len(ttfts),
            "failures": failures,
            "p95_ttft_ms": _p95_ms(ttfts) if ttfts else None,
            "mean_ttft_ms": round(sum(ttfts) / len(ttfts) * 1e3, 2)
            if ttfts else None,
            "requests_per_sec": round(len(ttfts) / wall, 2),
            "wall_s": round(wall, 3),
            "lora_cache": {
                **cache,
                "hit_ratio": round(cache["hits"] / total, 4)
                if total else 0.0,
            },
            # How many replicas actually took traffic: the spread the
            # adapter salt buys (oblivious: 1).
            "replicas_serving": sum(1 for n in served_by if n > 0),
            "served_by_replica": served_by,
        }
    finally:
        gw.stop()
        for s in servers:
            s.stop()


def main_spec(args) -> int:
    """--spec / --multilora: speculation + multi-LoRA serving record
    (artifact: SERVE_r10_spec.json, sections for whichever arms ran)."""
    global SPEC_K, SPEC_REQUESTS, SPEC_DECODE_TOKENS
    global ML_REPLICAS, ML_ADAPTERS, ML_CACHE_SLOTS, ML_LOAD_S
    global ML_ROUNDS, ML_CONCURRENCY
    if args.smoke:
        SPEC_K, SPEC_REQUESTS, SPEC_DECODE_TOKENS = 4, 2, 8
        ML_REPLICAS, ML_ADAPTERS, ML_CACHE_SLOTS = 2, 8, 4
        ML_LOAD_S, ML_ROUNDS, ML_CONCURRENCY = 0.01, 2, 8
    record: dict = {
        "model": "tiny",
        "provenance": "smoke" if args.smoke else "live",
        "host": _record_host(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    summary: dict = {}
    ok = True
    if args.spec:
        print(f"# spec arm: {SPEC_REQUESTS} requests x "
              f"{SPEC_DECODE_TOKENS} tokens, k_spec={SPEC_K} ...",
              file=sys.stderr)
        spec = run_spec_arm()
        record["speculative"] = spec
        summary.update({
            "spec_token_exact": spec["token_exact"],
            "spec_speedup": spec["speedup"],
            "spec_acceptance_rate": spec["acceptance_rate"],
        })
        ok = ok and spec["token_exact"]
        if not args.smoke:
            ok = ok and spec["speedup"] >= 1.5
    if args.multilora:
        print(f"# multilora affinity arm: {ML_ADAPTERS} adapters over "
              f"{ML_REPLICAS} replicas x {ML_ROUNDS} rounds ...",
              file=sys.stderr)
        affinity = run_multilora_arm("adapter_affinity", True)
        print("# multilora oblivious arm (fresh fleet) ...",
              file=sys.stderr)
        oblivious = run_multilora_arm("adapter_oblivious", False)
        record["multilora"] = {"affinity": affinity,
                               "oblivious": oblivious}
        summary.update({
            "ml_affinity_p95_ttft_ms": affinity["p95_ttft_ms"],
            "ml_oblivious_p95_ttft_ms": oblivious["p95_ttft_ms"],
            "ml_affinity_hit_ratio":
                affinity["lora_cache"]["hit_ratio"],
            "ml_oblivious_hit_ratio":
                oblivious["lora_cache"]["hit_ratio"],
            "ml_replicas_serving": affinity["replicas_serving"],
        })
        ok = ok and not affinity["failures"] and not oblivious["failures"]
        if not args.smoke:
            ok = (ok
                  and affinity["p95_ttft_ms"] < oblivious["p95_ttft_ms"]
                  and affinity["replicas_serving"] > 1)
    print(json.dumps(summary))
    if args.smoke:
        print("# --smoke: artifact write and win gate skipped",
              file=sys.stderr)
        return 0 if ok else 1
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(f"# wrote {args.out}", file=sys.stderr)
    if not ok:
        print("# r10 win gate FAILED", file=sys.stderr)
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--tenants", type=int, default=6)
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--churn-rounds", type=int, default=6)
    ap.add_argument("--disagg", action="store_true",
                    help="run the disaggregated prefill/decode tier "
                         "experiment instead of affinity-vs-random "
                         "(artifact: SERVE_r08_disagg.json)")
    ap.add_argument("--evict-storm", action="store_true",
                    help="run the HBM-economy eviction storm: bf16 "
                         "evict+re-prefill vs int8 KV + host-RAM swap "
                         "(artifact: SERVE_r09_hbm.json)")
    ap.add_argument("--spec", action="store_true",
                    help="run the speculative-decoding decode bench: "
                         "ragged spec scheduling vs plain ragged, "
                         "token-exact (artifact: SERVE_r10_spec.json)")
    ap.add_argument("--multilora", action="store_true",
                    help="run the 64-adapter multi-LoRA fleet: (prefix, "
                         "adapter) affinity vs adapter-oblivious routing "
                         "(artifact: SERVE_r10_spec.json)")
    ap.add_argument("--smoke", action="store_true",
                    help="2 replicas x 2 tenants x 2 rounds, no artifact, "
                         "no win gate — CI executability tier")
    args = ap.parse_args()
    root = Path(__file__).resolve().parent.parent
    if args.out is None:
        args.out = str(root / (
            "SERVE_r10_spec.json" if args.spec or args.multilora
            else "SERVE_r09_hbm.json" if args.evict_storm
            else "SERVE_r08_disagg.json" if args.disagg
            else "SERVE_r07_fleet.json"))
    if args.spec or args.multilora:
        return main_spec(args)
    if args.evict_storm:
        return main_evict(args)
    if args.disagg:
        return main_disagg(args)
    if args.smoke:
        global PREFIX_BLOCKS
        args.replicas, args.tenants = 2, 2
        args.rounds = args.churn_rounds = 2
        PREFIX_BLOCKS = 2  # executability tier: skip the long compiles

    wcb = -(-args.tenants // args.replicas) * PREFIX_BLOCKS
    kw = dict(replicas=args.replicas, tenants=args.tenants,
              rounds=args.rounds, warm_chain_blocks=wcb)
    print("# warming prefill/decode shapes ...", file=sys.stderr)
    _warm_shapes(wcb)
    print(f"# affinity arm: {args.replicas} replicas, {args.tenants} "
          f"tenants x {args.rounds} rounds ...", file=sys.stderr)
    affinity = run_arm("prefix", **kw)
    print(f"# random arm (fresh fleet) ...", file=sys.stderr)
    random_arm = run_arm("random", **kw)
    print("# churn phase: join + drain mid-run ...", file=sys.stderr)
    churn = run_churn(tenants=args.tenants, rounds=args.churn_rounds,
                      warm_chain_blocks=wcb)
    # Floor: the two measured arms' completions (warm-up and churn
    # completions only push the chain count higher).
    trace_summary = _verify_trace_export(
        affinity["requests_completed"] + random_arm["requests_completed"]
    )

    speedup = round(
        affinity["requests_per_sec"]
        / max(random_arm["requests_per_sec"], 1e-9), 3)
    record = {
        "scenario": (
            f"{args.tenants} tenants with {PREFIX_BLOCKS}-block shared "
            f"system prompts over {args.replicas} prefix-cached replicas; "
            "per-replica block pool holds only its fair share of warm "
            "chains"
        ),
        "model": "tiny",
        "replicas": args.replicas,
        "tenants": args.tenants,
        "rounds": args.rounds,
        "block_size": BLOCK_SIZE,
        "prefix_blocks": PREFIX_BLOCKS,
        "provenance": "smoke" if args.smoke else "live",
        "host": _record_host(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "affinity": affinity,
        "random": random_arm,
        "churn": churn,
        "throughput_speedup": speedup,
        **({"trace_summary": trace_summary} if trace_summary else {}),
    }
    print(json.dumps({
        "affinity_rps": affinity["requests_per_sec"],
        "random_rps": random_arm["requests_per_sec"],
        "throughput_speedup": speedup,
        "affinity_p95_ttft_ms": affinity["p95_ttft_ms"],
        "random_p95_ttft_ms": random_arm["p95_ttft_ms"],
        "affinity_hit_ratio": affinity["prefix_cache"]["hit_ratio"],
        "random_hit_ratio": random_arm["prefix_cache"]["hit_ratio"],
        "churn_failures": len(churn["failures"]),
        "telemetry_ttft_p95_ms": affinity["signals"]["ttft_p95_ms"],
        "slo_breaches": (affinity["slo"]["breaches_total"]
                         + random_arm["slo"]["breaches_total"]),
    }))
    # SLO gate: a healthy run must report ZERO breaches, and the
    # telemetry plane's TTFT p95 must agree with the clients' own
    # measurement — otherwise the autoscaler's future input is lying.
    slo_clean = all(
        arm["signals"]["agrees_within_15pct"]
        and arm["slo"]["breaches_total"] == 0
        and not arm["slo"]["breaching"]
        for arm in (affinity, random_arm)
    )
    if not slo_clean:
        print("# SLO gate FAILED: "
              + json.dumps({
                  "affinity": {**affinity["signals"], **affinity["slo"]},
                  "random": {**random_arm["signals"],
                             **random_arm["slo"]},
              }), file=sys.stderr)
    clean = (
        not affinity["failures"] and not random_arm["failures"]
        and not churn["failures"] and churn["ring_converged"]
        and slo_clean
    )
    if args.smoke:
        # Executability proven; toy numbers must not persist where a
        # scoreboard could mistake them for a measurement.
        print("# --smoke: artifact write and win gate skipped",
              file=sys.stderr)
        return 0 if clean else 1
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(f"# wrote {args.out}", file=sys.stderr)
    win = (
        clean
        and speedup >= 1.2
        and affinity["p95_ttft_ms"] <= random_arm["p95_ttft_ms"]
    )
    return 0 if win else 1


if __name__ == "__main__":
    sys.exit(main())
