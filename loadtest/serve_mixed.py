#!/usr/bin/env python
"""Mixed prefill+decode serving load test: ragged vs admit-then-step.

Drives the SAME burst of requests (mostly short prompts with every 4th
at bucket length — the realistic skew where a padded-bucket admission
scan wastes the most — and more requests than slots so admissions keep
landing while earlier requests decode) through two PagedBatcher engines:

- ``baseline``: the legacy admit-then-step scheduler — each admission runs
  its prompt prefill as its own dispatch, serialized against the decode
  steps of already-running slots;
- ``ragged``: the ragged engine (PagedBatcher(ragged=True)) — every step
  is ONE fused dispatch carrying all active slots' decode tokens plus the
  admitting slots' prompt chunks under a per-step token budget, and an
  admission's final chunk samples its first token in the same dispatch.

Per-request TTFT is observed through the engine's ``on_token`` hook (first
token wall-clock minus burst start); throughput is total emitted tokens
over the run's wall time. Each engine gets one full warm-up run at
identical shapes so compile time never lands in the measured numbers.

The artifact (default SERVE_r06.json, written atomically) records BOTH
engines' p95 TTFT and tokens/sec in one file — the ragged engine's win
condition is ``ragged.p95_ttft_ms < baseline.p95_ttft_ms``.

Usage: python loadtest/serve_mixed.py [--out SERVE_r06.json] [--requests 48]
       [--model tiny] [--slots 8] [--steps 48] [--token-budget 64]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _p95_ms(values) -> float:
    """Nearest-rank p95 in milliseconds — ONE formula for every artifact
    field, so the baseline and ragged numbers can never drift."""
    return round(sorted(values)[max(0, int(0.95 * len(values)) - 1)] * 1e3, 2)


def _make_prompts(cfg, n: int, short: int, bucket: int):
    import jax

    rng = jax.random.randint(
        jax.random.PRNGKey(1), (n, bucket), 3, cfg.vocab_size
    )
    return [
        list(map(int, row))[: (bucket if i % 4 == 0 else short)]
        for i, row in enumerate(rng)
    ]


def _decode_lens(n: int, steps: int):
    """Per-request decode lengths cycling ½×/1×/1½× ``steps``: staggered
    retirements keep admissions landing WHILE other slots decode — the
    mixed regime the scenario exists to measure (uniform lengths retire
    whole waves at once, and admission never overlaps decode)."""
    cycle = (steps // 2, steps, steps * 3 // 2)
    return [max(1, cycle[i % 3]) for i in range(n)]


def run_engine(params, cfg, prompts, *, ragged: bool, slots: int,
               steps: int, bucket: int, token_budget: int) -> dict:
    from kubeflow_tpu.models.paged import PagedBatcher
    from kubeflow_tpu.models.serving import GenerationConfig

    block_size = 16
    lens = _decode_lens(len(prompts), steps)
    per_seq = -(-(bucket + max(lens)) // block_size) + 1
    num_blocks = slots * per_seq + 2

    def one_run() -> dict:
        pb = PagedBatcher(
            params, cfg,
            gen=GenerationConfig(max_new_tokens=max(lens), eos_id=-1),
            slots=slots, num_blocks=num_blocks, block_size=block_size,
            prompt_bucket=bucket,
            **({"ragged": True, "token_budget": token_budget}
               if ragged else {}),
        )
        first: dict[int, float] = {}
        total = 0

        def on_token(rid: int, token: int) -> None:
            nonlocal total
            total += 1
            if rid not in first:
                first[rid] = time.perf_counter() - t0

        pb.on_token = on_token
        # The burst: everything queued before the engine takes a step, so
        # TTFT includes the queue wait the scheduler is responsible for.
        t0 = time.perf_counter()
        for p, n in zip(prompts, lens):
            pb.submit(p, max_new_tokens=n)
        pb.run()
        wall = time.perf_counter() - t0
        ttfts = [first[rid] for rid in sorted(first)]
        out = {
            "p95_ttft_ms": _p95_ms(ttfts),
            "mean_ttft_ms": round(sum(ttfts) / len(ttfts) * 1e3, 2),
            "tokens_per_sec": round(total / wall, 2),
            "wall_s": round(wall, 3),
            "requests_completed": len(ttfts),
        }
        if ragged and pb.ragged_steps:
            out["batch_fill"] = round(
                pb.ragged_tokens / pb.ragged_steps / token_budget, 4
            )
        return out

    one_run()  # warm-up: identical shapes, so the measured run is compile-free
    return one_run()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(Path(__file__).resolve().parent.parent
                                         / "SERVE_r06.json"))
    ap.add_argument("--model", default="tiny")
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--steps", type=int, default=48)
    ap.add_argument("--short", type=int, default=16)
    ap.add_argument("--bucket", type=int, default=256)
    ap.add_argument("--token-budget", type=int, default=64)
    args = ap.parse_args()

    import jax

    from kubeflow_tpu.models import llama as L

    cfg = L.LLAMA_CONFIGS[args.model]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    prompts = _make_prompts(cfg, args.requests, args.short, args.bucket)
    kw = dict(slots=args.slots, steps=args.steps, bucket=args.bucket,
              token_budget=args.token_budget)

    print(f"# baseline (admit-then-step), {args.requests} requests ...",
          file=sys.stderr)
    baseline = run_engine(params, cfg, prompts, ragged=False, **kw)
    print(f"# ragged (fused mixed batches, budget {args.token_budget}) ...",
          file=sys.stderr)
    ragged = run_engine(params, cfg, prompts, ragged=True, **kw)

    device = jax.devices()[0]
    record = {
        "scenario": "mixed prefill+decode burst (1-in-4 bucket-length "
                    "prompts, rest short, 6x oversubscribed slots)",
        "model": args.model,
        "device": getattr(device, "device_kind", str(device)),
        "requests": args.requests,
        "slots": args.slots,
        "max_new_tokens": args.steps,
        "prompt_short": args.short,
        "prompt_bucket": args.bucket,
        "token_budget": args.token_budget,
        "provenance": "live",
        "host": "tpu" if jax.default_backend() in ("tpu", "axon")
        else "cpu",
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "baseline": baseline,
        "ragged": ragged,
        "ttft_p95_speedup": round(
            baseline["p95_ttft_ms"] / max(ragged["p95_ttft_ms"], 1e-9), 3
        ),
    }
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, args.out)
    print(json.dumps({k: record[k] for k in
                      ("baseline", "ragged", "ttft_p95_speedup")}))
    print(f"# wrote {args.out}", file=sys.stderr)
    return 0 if ragged["p95_ttft_ms"] < baseline["p95_ttft_ms"] else 1


if __name__ == "__main__":
    sys.exit(main())
