#!/usr/bin/env python
"""Notebook churn load test.

Reference parity: loadtest/start_notebooks.py spawns N Notebook CRs + PVCs
via kubectl to load-test the controller (reference
components/notebook-controller/loadtest/start_notebooks.py:1-12). This
version has two modes:

- default (no cluster needed): drives N TPU notebooks through the full
  in-process control plane (webhooks + both reconcilers + fake kubelet) and
  reports spawn metrics — reconcile calls per notebook and wall time, the
  in-process analog of the BASELINE.json p50-spawn north star.
- ``--emit-yaml DIR``: writes the N Notebook CRs as YAML for ``kubectl
  apply`` against a real cluster, like the reference does.

Usage: python loadtest/start_notebooks.py [-n 50] [--tpu | --cpu]
       python loadtest/start_notebooks.py --emit-yaml /tmp/nbs -n 10
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def run_inprocess(n: int, tpu: bool) -> dict:
    from tests.harness import cpu_notebook, make_env, tpu_notebook
    from kubeflow_tpu.k8s import add_tpu_node_pool

    env = make_env(webhooks=True, platform=True)
    if tpu:
        # One 4-host slice pool per notebook: churn tests the control plane,
        # not scheduler backpressure (Pending-on-full-pool has its own test).
        for i in range(1, n):
            add_tpu_node_pool(
                env.cluster, "tpu-v5-lite-podslice", "4x4",
                hosts=4, chips_per_host=4, name_prefix=f"tpu-pool{i}",
            )
    spawn_calls = []
    spawn_wall = []
    t_total = time.perf_counter()
    for i in range(n):
        name = f"load-{i}"
        nb = tpu_notebook(name=name) if tpu else cpu_notebook(name=name)
        t0 = time.perf_counter()
        env.cluster.create(nb)
        calls = env.manager.run_until_idle(max_cycles=500)
        spawn_wall.append(time.perf_counter() - t0)
        spawn_calls.append(calls)
        obj = env.cluster.get("Notebook", name, "ns")
        ready = obj.get("status", {}).get("readyReplicas", 0)
        if ready < 1:
            raise SystemExit(f"{name} never became ready (readyReplicas={ready})")
    total = time.perf_counter() - t_total
    if env.manager.reconcile_errors:
        raise SystemExit(f"reconcile errors: {env.manager.reconcile_errors[:3]}")
    return {
        "notebooks": n,
        "mode": "tpu-4x4" if tpu else "cpu",
        "total_wall_s": round(total, 3),
        "p50_spawn_wall_ms": round(statistics.median(spawn_wall) * 1e3, 2),
        "p95_spawn_wall_ms": round(
            sorted(spawn_wall)[max(0, int(0.95 * n) - 1)] * 1e3, 2
        ),
        "p50_reconcile_calls": statistics.median(spawn_calls),
        "notebooks_per_sec": round(n / total, 1),
    }


def emit_yaml(n: int, tpu: bool, out_dir: Path) -> None:
    import yaml

    from kubeflow_tpu.api.notebook import TPUSpec, new_notebook

    out_dir.mkdir(parents=True, exist_ok=True)
    for i in range(n):
        nb = new_notebook(
            f"load-{i}",
            "loadtest",
            image="jax-notebook:latest" if tpu else "jupyter-minimal:latest",
            tpu=TPUSpec("v5e", "4x4") if tpu else None,
        )
        (out_dir / f"load-{i}.yaml").write_text(yaml.safe_dump(nb, sort_keys=False))
    print(f"wrote {n} Notebook CRs to {out_dir}; kubectl apply -f {out_dir}/")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", type=int, default=50)
    parser.add_argument("--cpu", action="store_true", help="single-pod CPU notebooks")
    parser.add_argument("--emit-yaml", type=Path, default=None)
    args = parser.parse_args()
    tpu = not args.cpu
    if args.emit_yaml:
        emit_yaml(args.n, tpu, args.emit_yaml)
        return 0
    print(json.dumps(run_inprocess(args.n, tpu)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
