#!/usr/bin/env python
"""Notebook churn load test.

Reference parity: loadtest/start_notebooks.py spawns N Notebook CRs + PVCs
via kubectl to load-test the controller (reference
components/notebook-controller/loadtest/start_notebooks.py:1-12). This
version has two modes:

- default (no cluster needed): drives N TPU notebooks through the full
  in-process control plane (webhooks + both reconcilers + fake kubelet) and
  reports spawn metrics — reconcile calls per notebook and wall time, the
  in-process analog of the BASELINE.json p50-spawn north star.
- ``--emit-yaml DIR``: writes the N Notebook CRs as YAML for ``kubectl
  apply`` against a real cluster, like the reference does.

Usage: python loadtest/start_notebooks.py [-n 50] [--tpu | --cpu]
       python loadtest/start_notebooks.py --emit-yaml /tmp/nbs -n 10
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _p95_ms(values) -> float:
    """Nearest-rank p95 in milliseconds — ONE formula for every artifact
    field, so the in-process, wire, and per-phase numbers can never drift."""
    return round(sorted(values)[max(0, int(0.95 * len(values)) - 1)] * 1e3, 2)


def run_inprocess(n: int, tpu: bool) -> dict:
    from tests.harness import cpu_notebook, make_env, tpu_notebook
    from kubeflow_tpu.k8s import add_tpu_node_pool

    env = make_env(webhooks=True, platform=True)
    if tpu:
        # One 4-host slice pool per notebook: churn tests the control plane,
        # not scheduler backpressure (Pending-on-full-pool has its own test).
        for i in range(1, n):
            add_tpu_node_pool(
                env.cluster, "tpu-v5-lite-podslice", "4x4",
                hosts=4, chips_per_host=4, name_prefix=f"tpu-pool{i}",
            )
    spawn_calls = []
    spawn_wall = []
    t_total = time.perf_counter()
    for i in range(n):
        name = f"load-{i}"
        nb = tpu_notebook(name=name) if tpu else cpu_notebook(name=name)
        t0 = time.perf_counter()
        env.cluster.create(nb)
        calls = env.manager.run_until_idle(max_cycles=500)
        spawn_wall.append(time.perf_counter() - t0)
        spawn_calls.append(calls)
        obj = env.cluster.get("Notebook", name, "ns")
        ready = obj.get("status", {}).get("readyReplicas", 0)
        if ready < 1:
            raise SystemExit(f"{name} never became ready (readyReplicas={ready})")
    total = time.perf_counter() - t_total
    if env.manager.reconcile_errors:
        raise SystemExit(f"reconcile errors: {env.manager.reconcile_errors[:3]}")
    return {
        "notebooks": n,
        "mode": "tpu-4x4" if tpu else "cpu",
        "total_wall_s": round(total, 3),
        "p50_spawn_wall_ms": round(statistics.median(spawn_wall) * 1e3, 2),
        "p95_spawn_wall_ms": _p95_ms(spawn_wall),
        "p50_reconcile_calls": statistics.median(spawn_calls),
        "notebooks_per_sec": round(n / total, 1),
    }


def run_wire(n: int, tpu: bool = True, profile: bool = False) -> dict:
    """Spawn latency through the PRODUCTION wiring: apiserver over HTTP,
    both managers via their main() build paths on serve loops, admission
    over HTTPS with self-signed serving certs, kubelet on the far side of
    HTTP. Measures create → all hosts Ready per notebook — the wire-stack
    analog of the BASELINE.json p50 spawn north star (fake kubelet timing
    is synthetic, but regressions in reconcile round-trips show up)."""
    import subprocess
    import tempfile
    import threading

    from kubeflow_tpu import k8s
    from kubeflow_tpu.cmd import notebook_manager, platform_manager
    from kubeflow_tpu.k8s.envtest import EnvtestServer
    from kubeflow_tpu.k8s.manager import Manager, RealClock
    from kubeflow_tpu.k8s.real import RealClient
    from kubeflow_tpu.k8s.serve import serve
    from kubeflow_tpu.webhook.server import (
        MUTATE_PATH,
        VALIDATE_PATH,
        WebhookServer,
    )
    from tests.harness import cpu_notebook, tpu_notebook

    hosts = 4 if tpu else 1
    cluster = k8s.FakeCluster()
    if tpu:
        for i in range(n):
            k8s.add_tpu_node_pool(
                cluster, "tpu-v5-lite-podslice", "4x4",
                hosts=4, chips_per_host=4, name_prefix=f"tpu-pool{i}",
            )
    else:
        k8s.add_cpu_node(cluster, "cpu-node-0")
    server = EnvtestServer(cluster).start()
    clients: list[RealClient] = []

    def new_client() -> RealClient:
        c = RealClient(server.client_config())
        clients.append(c)
        return c

    cert_dir = tempfile.mkdtemp(prefix="kftpu-loadtest-")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048",
         "-keyout", f"{cert_dir}/tls.key", "-out", f"{cert_dir}/tls.crt",
         "-days", "1", "-nodes", "-subj", "/CN=webhook",
         "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
        check=True, capture_output=True,
    )
    platform = platform_manager.build(
        new_client(), env={"K8S_NAMESPACE": "opendatahub"},
        argv=["--kube-rbac-proxy-image", "proxy:v1"], clock=RealClock(),
    )
    webhook_server = WebhookServer(
        mutating_handler=platform.mutating_webhook.handle,
        validating_handler=platform.validating_webhook.handle,
        cert_dir=cert_dir, tls_profile=platform.tls_profile,
    )
    webhook_server.start()
    base = f"https://127.0.0.1:{webhook_server.port}"
    server.add_remote_webhook(
        "Notebook", mutate_url=base + MUTATE_PATH,
        validate_url=base + VALIDATE_PATH, ca_file=f"{cert_dir}/tls.crt",
    )
    core = notebook_manager.build(new_client(), env={}, clock=RealClock())
    kubelet_client = new_client()
    kubelet_manager = Manager(kubelet_client, clock=RealClock())
    k8s.FakeKubelet(kubelet_client).register(kubelet_manager)

    class _Shim:
        def __init__(self, m):
            self.manager = m

        def run_until_idle(self, max_cycles: int = 200):
            return self.manager.run_until_idle(max_cycles)

        def tick(self, seconds: float):
            return self.manager.tick(seconds)

    stop = threading.Event()
    threads = [
        threading.Thread(target=serve, args=(b, c, stop), daemon=True)
        for b, c in ((platform, clients[0]), (core, clients[1]),
                     (_Shim(kubelet_manager), kubelet_client))
    ]
    for t in threads:
        t.start()
    user = new_client()

    from kubeflow_tpu.k8s.errors import NotFoundError

    spawn_wall = []
    # Per-phase medians (profile mode): where inside create→ready the
    # wall time goes. Phases are cumulative offsets from create:
    #   create_rt  — user.create() returning (admission webhooks inline),
    #   sts        — StatefulSet visible (core manager reconcile #1),
    #   pods       — all host pods exist (kubelet pod fan-out),
    #   pods_ready — every pod reports Ready (kubelet status walk),
    #   ready      — notebook.status.readyReplicas == hosts (kubelet STS
    #                status + core manager status mirror).
    phases: dict = {k: [] for k in
                    ("create_rt", "sts", "pods", "pods_ready", "ready")}
    try:
        t_total = time.perf_counter()
        for i in range(n):
            name = f"load-{i}"
            nb = tpu_notebook(name=name) if tpu else cpu_notebook(name=name)
            t0 = time.perf_counter()
            user.create(nb)
            if profile:
                phases["create_rt"].append(time.perf_counter() - t0)
            t_sts = t_pods = t_pods_ready = None
            deadline = t0 + 120
            while time.perf_counter() < deadline:
                if profile and t_sts is None:
                    try:
                        user.get("StatefulSet", name, "ns")
                        t_sts = time.perf_counter() - t0
                    except NotFoundError:
                        time.sleep(0.002)
                        continue
                if profile and t_pods is None:
                    have = 0
                    for j in range(hosts):
                        try:
                            user.get("Pod", f"{name}-{j}", "ns")
                            have += 1
                        except NotFoundError:
                            break
                    if have < hosts:
                        time.sleep(0.002)
                        continue
                    t_pods = time.perf_counter() - t0
                if profile and t_pods_ready is None:
                    ok = 0
                    for j in range(hosts):
                        pod = user.get("Pod", f"{name}-{j}", "ns")
                        conds = pod.get("status", {}).get("conditions", [])
                        if any(c.get("type") == "Ready"
                               and c.get("status") == "True" for c in conds):
                            ok += 1
                    if ok < hosts:
                        time.sleep(0.002)
                        continue
                    t_pods_ready = time.perf_counter() - t0
                obj = user.get("Notebook", name, "ns")
                if obj.get("status", {}).get("readyReplicas", 0) >= hosts:
                    break
                time.sleep(0.01)
            else:
                raise SystemExit(f"{name} never became ready over the wire")
            spawn_wall.append(time.perf_counter() - t0)
            if profile:
                phases["sts"].append(t_sts)
                phases["pods"].append(t_pods)
                phases["pods_ready"].append(t_pods_ready)
                phases["ready"].append(spawn_wall[-1])
        total = time.perf_counter() - t_total
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
        webhook_server.stop()
        for c in clients:
            c.stop()
        server.stop()
    out = {
        "notebooks": n,
        "mode": ("tpu-4x4" if tpu else "cpu") + "-wire",
        "total_wall_s": round(total, 3),
        "p50_spawn_wall_ms": round(statistics.median(spawn_wall) * 1e3, 2),
        "p95_spawn_wall_ms": _p95_ms(spawn_wall),
        "notebooks_per_sec": round(n / total, 1),
    }
    if profile:
        out["phase_p50_ms"] = {
            k: round(statistics.median(v) * 1e3, 2)
            for k, v in phases.items() if v
        }
        out["phase_p95_ms"] = {
            k: _p95_ms(v) for k, v in phases.items() if v
        }
    return out


def emit_yaml(n: int, tpu: bool, out_dir: Path) -> None:
    import yaml

    from kubeflow_tpu.api.notebook import TPUSpec, new_notebook

    out_dir.mkdir(parents=True, exist_ok=True)
    for i in range(n):
        nb = new_notebook(
            f"load-{i}",
            "loadtest",
            image="jax-notebook:latest" if tpu else "jupyter-minimal:latest",
            tpu=TPUSpec("v5e", "4x4") if tpu else None,
        )
        (out_dir / f"load-{i}.yaml").write_text(yaml.safe_dump(nb, sort_keys=False))
    print(f"wrote {n} Notebook CRs to {out_dir}; kubectl apply -f {out_dir}/")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", type=int, default=50)
    parser.add_argument("--cpu", action="store_true", help="single-pod CPU notebooks")
    parser.add_argument("--emit-yaml", type=Path, default=None)
    parser.add_argument(
        "--wire", action="store_true",
        help="run through the production wiring (HTTP apiserver + HTTPS "
             "admission + serve loops) instead of in-process",
    )
    parser.add_argument(
        "--artifact", type=Path, default=None,
        help="also write the JSON result to this path (round-over-round "
             "spawn-latency tracking, e.g. SPAWN_r03.json)",
    )
    parser.add_argument(
        "--profile", action="store_true",
        help="(wire mode) record per-phase p50/p95: create round-trip, "
             "STS visible, pods created, pods Ready, status ready — "
             "attributes regressions to the reconcile leg that moved",
    )
    args = parser.parse_args()
    tpu = not args.cpu
    if args.emit_yaml:
        emit_yaml(args.n, tpu, args.emit_yaml)
        return 0
    result = (
        run_wire(args.n, tpu, profile=args.profile)
        if args.wire else run_inprocess(args.n, tpu)
    )
    line = json.dumps(result)
    print(line)
    if args.artifact:
        args.artifact.write_text(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
